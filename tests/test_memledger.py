"""ISSUE 18 acceptance: the HBM memory ledger.

- byte-exact conservation: ``grants − frees == held`` holds EXACTLY —
  per subsystem and total — after every tick of the full lifecycle
  matrix (paged admission + COW divergence + prefix share + preempt
  park/resume + spec decode + int8 weight store), and a retired cohort
  returns the KV line exactly to its pre-admission baseline (the leak
  pin);
- exhaustion forensics: a refused admit leaves a ranked top-holders
  dump on the ledger (and the refused head's causal event carries the
  headroom that refused it); a bounded-intake shed is annotated the
  same way;
- eviction candidates: parked victims and sole-reader shared prefixes
  rank coldest-first by last-touch tick in ``Server.stats()``;
- the ``obs capacity`` CLI exit grammar (0 verdict / 2 no ledger data)
  and the ``obs diff`` memory gate (peak-held growth trips, absent
  ledger data never gates vacuously);
- reconciliation honesty: off-TPU reports carry the platform label and
  ledger-modeled bytes, never fabricated device numbers.

Wall discipline: ONE compiled paged engine (int8 weights) + ONE dense
spec engine for the whole module, reset per test (the test_trace
idiom).
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import pytest

from mpit_tpu.models import GPT2, GPT2Config
from mpit_tpu.obs import memledger as ml_mod
from mpit_tpu.obs import baseline
from mpit_tpu.obs.memledger import (
    MEMLEDGER_FORMAT,
    MemLedger,
    capacity_report,
    format_capacity,
)
from mpit_tpu.obs.__main__ import main as obs_cli
from mpit_tpu.serve import Engine, Request, SchedulingPolicy, Server
from mpit_tpu.serve.weights import params_wire_bytes

CFG = GPT2Config.tiny(max_seq_len=128, num_layers=2)
SCFG = GPT2Config.tiny(
    vocab_size=64, max_seq_len=64, num_layers=2, num_heads=2, d_model=32,
    dtype=jnp.float32,
)
SDCFG = GPT2Config.tiny(
    vocab_size=64, max_seq_len=64, num_layers=1, num_heads=2, d_model=32,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return jax.jit(GPT2(CFG).init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]


@pytest.fixture(scope="module")
def paged_engine(params):
    """ONE compiled paged engine — int8 weight store, 3 slots so the
    exhaustion tests can hit "slot free, pages gone", small chunk so
    prefix shares cross chunk boundaries."""
    return Engine(
        CFG, params, slots=3, max_len=64, prefill_len=32,
        kv_pages=16, kv_page_size=8, prefill_chunk=8,
        weights_dtype="int8", decode_attention="reference",
    )


@pytest.fixture(scope="module")
def spec_engine():
    """ONE dense spec engine (separate draft checkpoint — its weights
    are a REAL second store, not an alias)."""
    sparams = jax.jit(GPT2(SCFG).init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    sdparams = jax.jit(GPT2(SDCFG).init)(
        jax.random.key(1), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return Engine(
        SCFG, sparams, slots=2, max_len=40, prefill_len=8,
        spec_k=2, draft_params=sdparams, draft_cfg=SDCFG,
    )


def _req(rid, prompt, *, new=3, priority=0, tenant="", target=0.0):
    return Request(rid=rid, prompt=list(prompt), max_new_tokens=new,
                   priority=priority, tenant=tenant, ttft_target_s=target)


def _drain_checked(server):
    """Drive the server to completion ONE tick at a time, asserting
    the conservation invariant after every tick — "after each e2e
    run" is easy; per-tick is the real pin."""
    while server._pending():
        server._run_tick()
        _assert_conserved(server.engine)
    return server.completed


def _assert_conserved(engine):
    """The tentpole invariant, checked from BOTH sides: the ledger's
    own arithmetic (granted − freed == held, exact) AND the ledger
    against allocator ground truth (held == physical pages × page
    bytes, bitwise)."""
    ml = engine.memledger
    con = ml.conservation()
    assert con["ok"], con
    for name, sub in con["subsystems"].items():
        assert sub["granted_bytes"] - sub["freed_bytes"] == (
            sub["held_bytes"]
        ), (name, sub)
    if getattr(engine, "page_bytes", 0):
        alloc = engine.allocator
        assert ml.held("kv_pages") == alloc.pages_in_use * engine.page_bytes
        assert ml.held("kv_cow_reserve") == (
            alloc.reserved * engine.page_bytes
        )


# ---------------------------------------------------------------------------
# Unit: the ledger object alone (no engine, no jax arrays).
# ---------------------------------------------------------------------------


class TestMemLedgerUnit:
    def test_grant_free_conservation_exact(self):
        ml = MemLedger(platform="cpu")
        ml.register("pool", capacity_bytes=1000)
        ml.grant("pool", 300)
        ml.grant("pool", 200)
        ml.free("pool", 300)
        assert ml.held("pool") == 200
        assert ml.headroom("pool") == 800
        con = ml.conservation()
        assert con["ok"] and con["subsystems"]["pool"]["ok"]
        assert con["subsystems"]["pool"]["granted_bytes"] == 500
        assert con["subsystems"]["pool"]["freed_bytes"] == 300

    def test_over_free_breaks_conservation_loudly(self):
        """No clamping: an over-free goes NEGATIVE and the verdict
        names the violator — silent clamping would hide exactly the
        instrumentation bug conservation exists to catch."""
        ml = MemLedger()
        ml.grant("pool", 100)
        ml.free("pool", 150)
        assert ml.held("pool") == -50
        con = ml.conservation()
        assert not con["subsystems"]["pool"]["ok"]
        assert not con["ok"]

    def test_nested_subsystem_decomposes_without_double_count(self):
        ml = MemLedger()
        ml.grant("kv_pool", 1000)
        ml.register("kv_pages", capacity_bytes=800, nested_in="kv_pool")
        ml.grant("kv_pages", 600)
        assert ml.held() == 1000  # nested view, not additional memory
        assert ml.decompose() == {"kv_pages": 600, "kv_pool": 1000}
        snap = ml.snapshot()
        assert snap["subsystems"]["kv_pages"]["nested_in"] == "kv_pool"

    def test_headroom_none_without_declared_capacity(self):
        ml = MemLedger()
        ml.grant("pool", 10)
        assert ml.headroom("pool") is None

    def test_owner_recency_touch_forget(self):
        ml = MemLedger()
        ml.grant("kv", 64, owner="r1", tenant="acme", tick=3)
        ml.touch("r1", tick=9)
        ml.touch("r1", tick=5)  # stale touch never rewinds recency
        assert ml.owners()["r1"]["last_touch"] == 9
        ml.forget("r1")
        assert "r1" not in ml.owners()

    def test_reset_transients_keeps_byte_accumulators(self):
        ml = MemLedger()
        ml.grant("pool", 100, owner="r1", tick=1)
        ml.note_exhaustion({"tick": 1})
        ml.reset_transients()
        assert ml.owners() == {}
        assert "exhaustion" not in ml.snapshot()
        assert ml.held("pool") == 100  # bytes survive: still held

    def test_watermark_tracks_peak(self):
        ml = MemLedger()
        ml.grant("pool", 500, tick=1)
        ml.free("pool", 400, tick=2)
        ml.grant("pool", 100, tick=3)
        wm = ml.watermark()
        assert wm["held_peak_bytes"] == 500 and wm["tick"] == 1
        assert wm["subsystems"]["pool"] == 500

    def test_reconcile_off_tpu_never_fabricates_device_bytes(self):
        """The roofline honesty rule: a cpu-platform ledger reports
        modeled bytes + platform label even when handed a device
        object that WOULD answer memory_stats()."""

        class FakeDev:
            def memory_stats(self):
                return {"bytes_in_use": 999}

        ml = MemLedger(platform="cpu")
        ml.grant("pool", 100)
        rec = ml.reconcile(FakeDev())
        assert rec["platform"] == "cpu"
        assert rec["ledger_bytes"] == 100
        assert rec["device_bytes"] is None
        assert rec["within_tolerance"] is None

    def test_reconcile_on_tpu_compares_within_tolerance(self):
        class FakeDev:
            def memory_stats(self):
                return {"bytes_in_use": 105}

        ml = MemLedger(platform="tpu")
        ml.grant("pool", 100)
        rec = ml.reconcile(FakeDev(), tolerance_pct=10.0)
        assert rec["device_bytes"] == 105
        assert rec["within_tolerance"] is True
        rec = ml.reconcile(FakeDev(), tolerance_pct=1.0)
        assert rec["within_tolerance"] is False

    def test_snapshot_format_and_exhaustion_retained(self):
        ml = MemLedger(platform="cpu")
        ml.grant("pool", 100)
        ml.note_exhaustion({"tick": 7, "top_holders": []})
        snap = ml.snapshot()
        assert snap["format"] == MEMLEDGER_FORMAT
        assert snap["exhaustion"]["tick"] == 7
        assert snap["exhaustions"] == 1
        json.dumps(snap)  # serializable as-is


# ---------------------------------------------------------------------------
# Offline verdicts: capacity_report + the CLI exit grammar.
# ---------------------------------------------------------------------------


class TestCapacityVerdict:
    def _snap(self):
        ml = MemLedger(platform="cpu")
        ml.register("kv_pages", capacity_bytes=800, nested_in="kv_pool")
        ml.grant("kv_pool", 1000)
        ml.grant("kv_pages", 600)
        ml.grant("weights", 5000)
        return ml.snapshot()

    def test_report_from_raw_snapshot(self):
        rep = capacity_report(self._snap())
        assert rep["held_bytes"] == 6000
        assert rep["kv_capacity_bytes"] == 800
        assert rep["kv_headroom_bytes"] == 200
        assert rep["conservation_ok"]
        text = format_capacity(rep)
        assert "conservation: ok" in text and "weights" in text

    def test_report_names_host_tier_and_pressure(self):
        """ISSUE 20: a tiered stats block yields a host line, tiered
        eviction candidates, and an exhaustion verdict naming whether
        pressure is HBM-only or both tiers; a pre-tiering snapshot
        (``_snap``) keeps reporting with no host line at all."""
        mem = {
            "source": "memledger", "platform": "cpu",
            "held_bytes": 6000, "held_peak_bytes": 6000,
            "held_by_subsystem": {"kv_pages": 600, "weights": 5000},
            "conservation": {"ok": True},
            "kv_capacity_bytes": 800,
            "host_held_bytes": 4096, "host_capacity_bytes": 8192,
            "host_held_peak_bytes": 6144,
            "eviction_candidates": [
                {"kind": "host_prefix", "key": "prefix[16t]",
                 "bytes": 4096, "last_touch_tick": 3, "tier": "host"},
            ],
            "exhaustion": {"tick": 9, "kv_headroom_bytes": 0,
                           "tier_pressure": "both_tiers"},
        }
        rep = capacity_report({"memory": mem})
        assert rep["host_held_bytes"] == 4096
        assert rep["host_capacity_bytes"] == 8192
        assert rep["host_held_peak_bytes"] == 6144
        text = format_capacity(rep)
        assert "host tier held 4.0KiB of 8.0KiB (50.0%)" in text
        assert "tier=host" in text
        assert "pressure=both_tiers" in text
        # Pre-tiering snapshot: no host subsystem, no host line.
        pre = capacity_report(self._snap())
        assert "host_held_bytes" not in pre
        assert "host tier" not in format_capacity(pre)

    def test_report_refuses_docs_without_ledger_data(self):
        with pytest.raises(ValueError):
            capacity_report({"phases": {}})
        with pytest.raises(ValueError):
            capacity_report({"workloads": {"alexnet": {}}})

    def test_cli_exit_0_on_snapshot_2_without_ledger(self, tmp_path,
                                                     capsys):
        good = tmp_path / "snap.json"
        good.write_text(json.dumps(self._snap()))
        assert obs_cli(["capacity", str(good)]) == 0
        assert "capacity verdict" in capsys.readouterr().out
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"workloads": {"alexnet": {}}}))
        assert obs_cli(["capacity", str(bad)]) == 2
        assert "error" in capsys.readouterr().out


class TestBaselineMemoryGate:
    def _snap(self, peak, headroom_min=40.0):
        s = baseline.snapshot(
            {"phases": {"decode": {"count": 1, "total_s": 1.0,
                                   "p50_s": 1.0, "p95_s": 1.0}}},
            memory={"held_peak_bytes": peak,
                    "kv_headroom_min_pct": headroom_min,
                    "platform": "cpu"},
        )
        return s

    def test_peak_growth_beyond_tolerance_trips_gate(self):
        verdict = baseline.diff(
            self._snap(1000), self._snap(1300), tolerance_pct=10.0
        )
        assert not verdict["ok"]
        assert verdict["memory_regressions"] == ["memory.held_peak_bytes"]
        assert verdict["memory"]["held_peak_bytes"]["growth_pct"] == 30.0

    def test_growth_within_tolerance_passes_and_reports(self):
        verdict = baseline.diff(
            self._snap(1000, 40.0), self._snap(1050, 35.0),
            tolerance_pct=10.0,
        )
        assert verdict["ok"] and verdict["memory_regressions"] == []
        assert verdict["memory"]["kv_headroom_min_pct"]["cur"] == 35.0

    def test_snapshot_without_ledger_data_never_gates_vacuously(self):
        """A pre-ledger baseline (no memory section) diffs clean on the
        memory dimension — no section, no vacuous verdict."""
        bare = baseline.snapshot(
            {"phases": {"decode": {"count": 1, "total_s": 1.0,
                                   "p50_s": 1.0, "p95_s": 1.0}}}
        )
        assert "memory" not in bare
        verdict = baseline.diff(bare, self._snap(99999999))
        assert verdict["ok"] and "memory" not in verdict

    def test_snapshot_drops_non_numeric_memory_blocks(self):
        s = baseline.snapshot(
            {"phases": {}}, memory={"held_peak_bytes": None}
        )
        assert "memory" not in s

    # -- host-tier keys (ISSUE 20) ---------------------------------------
    def _host_snap(self, peak, host_peak, restream=4096):
        return baseline.snapshot(
            {"phases": {"decode": {"count": 1, "total_s": 1.0,
                                   "p50_s": 1.0, "p95_s": 1.0}}},
            memory={"held_peak_bytes": peak, "platform": "cpu",
                    "host_held_peak_bytes": host_peak,
                    "restream_bytes": restream},
        )

    def test_host_peak_growth_beyond_tolerance_trips_gate(self):
        """Host-tier peak growth is a spill leak — granted at dispatch,
        never released — and gates exactly like the HBM peak."""
        verdict = baseline.diff(
            self._host_snap(1000, 2000), self._host_snap(1000, 2600),
            tolerance_pct=10.0,
        )
        assert not verdict["ok"]
        assert verdict["memory_regressions"] == [
            "memory.host_held_peak_bytes"
        ]
        assert verdict["memory"]["host_held_peak_bytes"][
            "growth_pct"] == 30.0
        # restream bytes ride along as context, never gate.
        assert verdict["memory"]["restream_bytes"] == {
            "base": 4096, "cur": 4096,
        }

    def test_pre_tiering_baseline_never_gates_host_keys(self):
        """A pre-ISSUE-20 baseline has no host keys: the diff must not
        manufacture a host verdict from one side (the HBM keys' own
        never-gate-vacuously rule, extended)."""
        verdict = baseline.diff(
            self._snap(1000), self._host_snap(1000, 99999999),
            tolerance_pct=10.0,
        )
        assert verdict["ok"]
        assert "host_held_peak_bytes" not in verdict.get("memory", {})
        # And a zero-peak base (tiering on, nothing ever spilled)
        # stays ungated too — growth from 0 is undefined, not infinite.
        verdict = baseline.diff(
            self._host_snap(1000, 0), self._host_snap(1000, 8192),
            tolerance_pct=10.0,
        )
        assert verdict["ok"]


# ---------------------------------------------------------------------------
# The serve stack: conservation across the lifecycle matrix.
# ---------------------------------------------------------------------------


class TestServeConservation:
    def test_weight_store_bytes_exact_int8(self, paged_engine):
        """The int8 weight store's ledger line equals the shared wire
        sizing rule over the quantized tree, bitwise — scale blocks
        included."""
        ml = paged_engine.memledger
        assert ml.held("weights") == params_wire_bytes(paged_engine.params)
        assert ml.held("weights") > 0

    def test_conservation_every_tick_with_cow_and_prefix_share(
        self, paged_engine
    ):
        """The matrix core: cold admit, prefix share (B extends A's
        registered prompt while A is live), COW divergence on the
        shared partial page, retirement — conservation checked after
        EVERY tick, and the retired cohort returns kv bytes exactly to
        the pre-admission baseline (the leak pin)."""
        engine = paged_engine
        engine.reset()
        ml = engine.memledger
        base_held = ml.held()
        assert ml.held("kv_pages") == 0
        server = Server(engine)
        prompt = list(range(1, 11))  # 10 tokens: partial last page
        server.submit(_req("a", prompt, new=8, tenant="acme"))
        server.run(max_ticks=3)  # prefill done, prefixes registered
        server.submit(_req("b", prompt + [11, 12], new=6, tenant="beta"))
        done = _drain_checked(server)
        assert {c.rid for c in done} == {"a", "b"}
        assert engine.allocator.prefix_hits >= 1  # b shared a's pages
        assert engine.allocator.cow_copies >= 1  # divergence copied
        _assert_conserved(engine)
        # Leak pin: everything the cohort held came back, exactly.
        assert ml.held("kv_pages") == 0
        assert ml.held("kv_cow_reserve") == 0
        assert ml.held() == base_held

    def test_preempt_park_resume_conserves_and_ranks_victim(
        self, paged_engine
    ):
        """Preemption parks a victim (pages freed -> ledger frees),
        resume re-admits (re-grant); while parked the victim shows up
        as the COLDEST eviction candidate with its projected
        re-admission claim."""
        engine = paged_engine
        engine.reset()
        ml = engine.memledger
        server = Server(engine, policy=SchedulingPolicy())
        server.submit(_req("v", list(range(1, 11)), new=8, priority=1,
                           tenant="acme"))
        server.run(max_ticks=6)
        assert server.live
        server._preempt(next(iter(server.live)))
        _assert_conserved(engine)
        mem = server.stats()["memory"]
        kinds = [c["kind"] for c in mem["eviction_candidates"]]
        assert "parked_victim" in kinds
        victim = next(c for c in mem["eviction_candidates"]
                      if c["kind"] == "parked_victim")
        assert victim["rid"] == "v" and victim["bytes"] > 0
        ticks = [c["last_touch_tick"] for c in mem["eviction_candidates"]]
        assert ticks == sorted(ticks)  # coldest first
        done = _drain_checked(server)
        assert len(done) == 1 and server.policy.resumes == 1
        assert ml.held("kv_pages") == 0

    def test_sole_reader_prefix_ranks_while_registrant_lives(
        self, paged_engine
    ):
        """A live request's registered prefixes are refcount-1 — the
        sole-reader entries an eviction policy could reclaim by
        retiring one idle mapper."""
        engine = paged_engine
        engine.reset()
        server = Server(engine)
        server.submit(_req("a", list(range(1, 18)), new=12))
        server.run(max_ticks=8)  # prefilled + registered, still live
        assert server.live
        mem = server.stats()["memory"]
        sole = [c for c in mem["eviction_candidates"]
                if c["kind"] == "sole_reader_prefix"]
        assert sole and all(c["bytes"] > 0 for c in sole)
        assert mem["per_request"]["a"]["bytes"] > 0
        assert mem["per_tenant"][""] == mem["per_request"]["a"]["bytes"]
        server.run()

    def test_memory_stats_attribution_matches_ledger(self, paged_engine):
        """Cross-check identity: per-request exclusive bytes + distinct
        shared-page bytes == the kv_pages ledger line, exactly."""
        engine = paged_engine
        engine.reset()
        server = Server(engine)
        prompt = list(range(1, 11))
        server.submit(_req("a", prompt, new=10, tenant="acme"))
        server.run(max_ticks=3)
        server.submit(_req("b", prompt + [11], new=8, tenant="beta"))
        server.run(max_ticks=3)
        mem = server.stats()["memory"]
        exclusive = sum(e["bytes"] for e in mem["per_request"].values())
        assert exclusive + mem["shared_bytes"] == (
            engine.memledger.held("kv_pages")
        )
        assert mem["conservation"]["ok"]
        assert mem["reconciliation"]["platform"] != "tpu"
        assert mem["reconciliation"]["device_bytes"] is None
        server.run()


class TestExhaustionForensics:
    def test_exhaustion_dump_ranks_holders_and_carries_headroom(
        self, paged_engine
    ):
        """Pool exhausted with a slot free: the ledger retains the
        ranked top-holders dump, and the refused head's admit_blocked
        event carries the headroom numbers that refused it."""
        from mpit_tpu.obs.trace import Ledger

        engine = paged_engine
        engine.reset()
        led = Ledger(mode="full", exemplar_k=8)
        server = Server(engine, ledger=led)
        big = list(range(1, 31))  # 30 + 20 - 1 -> 7 pages of 16
        server.submit(_req("h1", big, new=20, tenant="acme"))
        server.submit(_req("h2", big[::-1], new=20, tenant="acme"))
        server.submit(_req("h3", list(range(31, 61)), new=20,
                           tenant="beta"))
        server.run(max_ticks=4)  # h1/h2 hold 14 pages; h3 blocked
        snap = engine.memledger.snapshot()
        assert snap["exhaustions"] >= 1
        dump = snap["exhaustion"]
        assert dump["free_pages"] == 2 and dump["queued"] == 1
        holders = dump["top_holders"]
        assert {h["rid"] for h in holders} == {"h1", "h2"}
        bys = [h["bytes"] for h in holders]
        assert bys == sorted(bys, reverse=True) and bys[0] > 0
        assert dump["tenants"]["acme"] == sum(bys)
        assert "kv_headroom_bytes" in dump and "subsystems" in dump
        headroom_then = 2 * engine.page_bytes
        server.run()  # h1/h2 retire; h3 admits and finishes
        _assert_conserved(engine)
        ex = next(e for e in led.exemplars() if e["rid"] == "h3")
        blocked = next(a for k, _, a in ex["events"]
                       if k == "admit_blocked")
        assert blocked["need_pages"] == 7
        assert blocked["kv_headroom_bytes"] == headroom_then

    def test_queue_full_shed_annotated_with_headroom(self, paged_engine):
        from mpit_tpu.obs.trace import Ledger

        engine = paged_engine
        engine.reset()
        led = Ledger(mode="full", exemplar_k=8)
        server = Server(engine, max_queue=1, ledger=led)
        server.submit(_req("s1", list(range(1, 31)), new=20))
        server.submit(_req("s2", list(range(31, 61)), new=20))
        server.submit(_req("s3", list(range(61, 91)), new=20))
        server.run(max_ticks=2)
        assert server.shed_causes.get("queue_full", 0) >= 1
        ex = next(e for e in led.exemplars() if e["status"] == "shed")
        shed = next(a for k, _, a in ex["events"] if k == "shed")
        assert "kv_headroom_bytes" in shed and "hbm_held_bytes" in shed
        server.run()


class TestSpecAndDense:
    def test_spec_engine_conserves_with_separate_draft_store(
        self, spec_engine
    ):
        """Spec decode (dense engine, separate draft checkpoint): the
        draft weights are a REAL second ledger line, the kv_pool line
        covers target + draft caches, kv_slots grants/frees conserve
        across accept/rollback, and retirement returns the slots."""
        engine = spec_engine
        engine.reset()
        ml = engine.memledger
        assert ml.held("draft_weights") > 0  # no alias: separate bytes
        assert ml.held("draft_weights") < ml.held("weights")
        server = Server(engine)
        server.submit(_req("s1", [5, 9, 3], new=6))
        server.submit(_req("s2", [7, 2], new=5))
        done = _drain_checked(server)
        assert len(done) == 2
        assert server.stats()["spec_accepted_tokens"] >= 0
        _assert_conserved(engine)
        assert ml.held("kv_slots") == 0

    def test_dense_memory_stats_block(self, spec_engine):
        engine = spec_engine
        engine.reset()
        server = Server(engine)
        server.submit(_req("d1", [5, 9, 3], new=12))
        server.run(max_ticks=2)
        assert server.live  # still decoding: the slot grant is held
        mem = server.stats()["memory"]
        assert mem["source"] == "memledger"
        assert mem["held_by_subsystem"]["kv_slots"] == engine.slot_bytes
        assert mem["kv_capacity_bytes"] == 2 * engine.slot_bytes
        assert mem["per_request"]["d1"]["bytes"] == engine.slot_bytes
        server.run()
        assert engine.memledger.held("kv_slots") == 0

    def test_engine_reset_returns_every_kv_byte(self, paged_engine):
        """reset() mid-flight conserves: live slots' pages are freed
        through the ledger, not orphaned."""
        engine = paged_engine
        engine.reset()
        server = Server(engine)
        server.submit(_req("r1", list(range(1, 11)), new=10))
        server.run(max_ticks=4)
        assert engine.memledger.held("kv_pages") > 0
        engine.reset()
        assert engine.memledger.held("kv_pages") == 0
        assert engine.memledger.held("kv_cow_reserve") == 0
        assert engine.memledger.conservation()["ok"]
