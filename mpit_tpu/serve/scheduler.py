"""Continuous batching: the request loop over the slot-batched engine.

The reference's pserver is a tag-dispatched request-serving loop
(SURVEY.md §3.2 A1) — receive, act, reply, forever. This is that
capability rebuilt for inference: requests queue on the host, are
admitted into freed KV-cache slots BETWEEN decode ticks (no tick waits
for a full batch — a new request rides the next prefill while everyone
else keeps decoding), and retire per-slot on EOS / max-new-tokens /
cache-full, freeing the slot for the next queue entry immediately.

Observability (``mpit_tpu.obs``) is first-class, not bolted on:

- spans: ``prefill`` (per admission batch) and ``decode`` (per tick) —
  both close on the host fetch of the sampled tokens, so their wall
  clock covers real device completion;
- per-request intervals recorded with explicit timestamps
  (``obs.span_at``): ``queue_wait`` (submit → admit), ``request_ttft``
  (submit → first token) and ``request_latency`` (submit → retire) —
  the summary's per-phase p50/p95 roll-up then IS the latency/TTFT
  histogram, and the Chrome trace shows every request as a bar;
- ``slot_occupancy`` gauge + ``serve_tokens``/``serve_requests``
  counters each tick.

An optional :class:`mpit_tpu.obs.Sentinel` (``phases=("decode",
"prefill")``) watches the tick stream for spikes/sustained degradation
— the serving analogue of the training loop's step-wall sentinel.

ISSUE 6 grows the loop production-shaped:

- **Streaming telemetry**: ``Server(stream=StreamRegistry())`` feeds
  per-request TTFT/latency/queue-wait into rolling-window histogram
  sketches and per-tick token/arrival rates + queue/occupancy gauges —
  live percentiles over the last N seconds at O(buckets) memory, so a
  sustained run's telemetry never depends on the Recorder's bounded
  event buffer (``obs.stream``).
- **SLO monitoring**: ``Server(slo=SLOMonitor(...))`` evaluates
  declared targets (p95 TTFT ≤ X, shed-rate ≤ Z, ...) against those
  windows once per tick; breach transitions emit ``slo_breach`` /
  ``slo_recovered`` instants and feed the sentinel (``obs.slo``).
- **Timed drive**: :meth:`Server.run_timed` admits an OPEN-loop
  arrival trace (``serve.loadgen``) by its arrival clock — requests
  are submitted when due, never up front, so offered load is a
  property of the trace, not of how fast the server drains.
- **Request lifelines**: per-request spans carry ``rid`` (and
  ``tenant`` when set) and batch spans carry ``rids``, so one
  request's queue-wait → prefill → decode path is filterable in the
  Perfetto export.
- **Bounded intake**: ``Server(max_queue=N)`` sheds arrivals beyond N
  queued (counted in ``serve_shed`` / ``Server.shed`` — the shed-rate
  SLO's numerator); unbounded by default.

ISSUE 8 (roofline): every decode tick feeds the LENGTH-AWARE achieved
HBM bytes — the engine's visited-tile model, pinned against the
kernel's own in-kernel count — into the recorder's work accounting
(``obs.roofline.work``), the rolling stream windows
(``decode_hbm_bytes`` / ``decode_flops`` rates → the CLI's
``hbmbw=``/``mfu=`` fields) and a sustained-collapse watch; the
engine's CompileWatch is wired to this server's sentinel, so an
unexpected mid-service recompile and a collapsing work rate land in
the same anomaly report as tick-duration spikes. ``stats()`` carries
``engine_compiles`` (the pinned lifetime count) and
``decode_hbm_bytes_modeled``.

ISSUE 7 (paged engine): admission becomes a PAGE grant, not just a slot
grant — the head of the queue gets a free slot plus its whole page
requirement (fresh pages + shared-prefix mappings + COW reserve,
all-or-nothing) or waits; prompts feed the device ``prefill_chunk``
tokens per tick interleaved with decode (``prefilling`` state — a long
admit cannot head-of-line-block TTFT for live slots); a finished prompt
is registered in the allocator's prefix index so later identical
prefixes map the same pages (refcounted, copy-on-write on divergence —
the scheduler calls ``cow_before_write`` before every prefill-chunk /
decode write and runs the device page copy it returns); retirement
frees the slot's pages back to the pool. ``kv_tokens_cached`` /
``kv_pool_occupancy`` / ``prefix_pages_shared`` gauges land in the
Recorder and the stream windows each tick.

ISSUE 12 (scheduling policy): ``Server(policy=SchedulingPolicy(...))``
replaces the FIFO deque with the policy tier (``serve.policy``) —
priority-ordered tenant-fair queues consulted at every admit boundary,
projected-TTFT admission shedding at submit (``shed_admission``,
distinct from ``max_queue``'s ``shed_queue_full`` in every counter /
instant / stats key), and preemption on the paged engine: when the
best queued tier's head is projected to miss its TTFT target and
nothing frees, a lower-tier live generation is PARKED — pages freed
back to the allocator, generated-so-far tokens kept host-side — and
later resumed through the normal chunked-prefill path with
``feed = prompt + tokens`` (the resume prefill recomputes exactly the
decode tick the eviction displaced, so a preempted-then-resumed greedy
request bit-matches its un-preempted output — test-pinned). The
policy's projector reads ``prefill_tick`` / ``decode_tick`` rolling
windows this server feeds once per tick; per-tier TTFT series
(``request_ttft_tier<p>``) and per-tenant series
(``request_ttft_tenant:<t>``) land in the registry so SLOs and the
``stats()`` tenant roll-up can tell the classes apart. Without a
policy every path below is byte-for-byte the FIFO scheduler.

ISSUE 13 (speculative decoding): on an ``Engine(spec_k=k, ...)`` the
decode tick becomes :meth:`Server._spec_tick` — draft ``k`` tokens per
live slot, verify all ``k+1`` positions in one target pass, append each
slot's emitted prefix and retire exactly as the plain tick would (EOS /
token budget are clamped IN-STEP, so device lengths and the host token
lists never diverge). The tick is spanned ``decode`` with nested
``spec_draft`` / ``spec_verify`` spans (the ``attention=`` idiom on all
three); ``accepted_tokens_per_tick`` (emitted per slot-tick, 1.0 =
plain decode) and ``draft_acceptance_rate`` feed the rolling windows
and ``stats()`` — the ``gpt2_serve`` record line carries the former.
Submit validation grows the dense-engine headroom check (the verify
writes ``k+1`` rows at the fill; ``prompt + max_new + k - 1`` must fit
``max_len`` — the paged engine instead DROPS out-of-range rows).
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import Any

import numpy as np

from mpit_tpu import obs
from mpit_tpu.ops.decode_attention import num_kv_blocks

__all__ = ["Request", "Completed", "Server", "warm_engine"]


def warm_engine(engine, *, register_costs: bool = False) -> None:
    """Pay the engine's lifetime XLA compiles (prefill + decode — or
    prefill + spec_draft + spec_verify on a speculative engine) with
    one throwaway request, then reset the cache — call BEFORE any timed
    window so an open-loop harness's first arrivals measure the server,
    not the compiler. Prompt content is irrelevant: the padded
    prefill/decode buffers fix the traced shapes.

    The whole warm run is spanned as ``warmup`` (ISSUE 8 satellite:
    warmup time is attributed, not a silent gap in the trace), and the
    compiles it triggers land as ``compile`` spans + the
    ``engine_compiles`` gauge via the engine's CompileWatch.
    ``register_costs=True`` additionally registers the steps'
    ``cost_analysis()`` costs with the recorder
    (:meth:`~mpit_tpu.serve.engine.Engine.register_roofline`) — opt-in
    because it re-compiles each step once for the cost query; bench and
    the serve CLI pass it, parity tests don't pay it."""
    with obs.span("warmup"):
        warm = Server(engine)
        warm.submit(Request(rid="warm", prompt=[1, 2, 3], max_new_tokens=2))
        warm.run()
        if getattr(engine, "paged", False):
            # The COW device copy is its own (tiny) compile — a lone
            # warm request never diverges from a shared page, so pay it
            # here or the first real divergence pays it inside the
            # timed window.
            engine.copy_page(0, 0)
            if getattr(engine, "host_pages", 0):
                # The host tier's gather/scatter pair likewise: pay
                # both compiles with a page-0 round trip (restore
                # rewrites exactly what spill read — a semantic no-op).
                engine.spill_page(0, 0)
                engine.drain_spills()
                engine.restore_page(0, 0, release=True, kind="warm")
        if register_costs:
            engine.register_roofline()
    engine.reset()


@dataclasses.dataclass
class Request:
    """One generation request. ``temperature <= 0`` = greedy;
    ``top_k = 0`` = full vocab; ``eos_id = None`` = never stop early;
    ``tenant`` labels the requester (multi-tenant load traces) and is
    stamped on the request's spans when non-empty. ``priority`` is the
    scheduling-policy tier (0 = highest / interactive; ignored by the
    FIFO scheduler) and ``ttft_target_s`` the per-request TTFT SLO the
    policy's admission/preemption decisions are made against (<= 0 =
    no target)."""

    rid: Any
    prompt: list[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    eos_id: int | None = None
    tenant: str = ""
    priority: int = 0
    ttft_target_s: float = 0.0


@dataclasses.dataclass
class Completed:
    """A finished request: output + the latency facts the histograms
    aggregate. ``tokens`` includes the EOS token when one stopped it."""

    rid: Any
    prompt: list[int]
    tokens: list[int]
    submit_t: float
    first_token_t: float
    finish_t: float
    truncated: bool = False  # retired by cache-full, not EOS/max-tokens
    tenant: str = ""

    @property
    def ttft_s(self) -> float:
        return self.first_token_t - self.submit_t

    @property
    def latency_s(self) -> float:
        return self.finish_t - self.submit_t


@dataclasses.dataclass
class _Live:
    req: Request
    submit_t: float
    first_token_t: float = 0.0
    tokens: list = dataclasses.field(default_factory=list)
    # Paged-engine prefill state (ISSUE 7): ``base`` = prompt tokens
    # already cached (advanced per chunk), ``floor`` = the shared-prefix
    # write floor granted at admission (positions below it live in
    # immutable shared pages).
    base: int = 0
    floor: int = 0
    # Preemption state (ISSUE 12): ``feed`` = the token sequence to
    # (re-)prefill — ``None`` until a preemption parks the request, then
    # prompt + generated-so-far tokens (the resume prefill's last row IS
    # the decode tick the eviction displaced, which is what makes the
    # resumed greedy output bit-match). ``preempts`` bounds thrash.
    feed: list | None = None
    preempts: int = 0
    # Memory-ledger recency (ISSUE 18): the last tick this request's
    # cache bytes were touched (bind / prefill chunk / decode emit) and
    # the tick a preemption parked it — what the eviction-candidate
    # ranking orders by (coldest first).
    last_touch: int = 0
    park_tick: int = 0
    # Host-tier resume telemetry (ISSUE 20): how the last resume
    # rebuilt this slot's cache ("restream" from parked host pages /
    # "recompute" through chunked re-prefill) and when it was
    # re-admitted — cleared once the resume completes (first
    # post-resume token), closing the per-mode duration sample.
    resume_mode: str = ""
    resume_t: float = 0.0

    def feed_tokens(self) -> list:
        """What prefill feeds the device: the prompt, or the resume
        sequence after a preemption."""
        return self.feed if self.feed is not None else self.req.prompt

    def remaining_new(self) -> int:
        """Output tokens still owed — the page requirement's generation
        term (full ``max_new_tokens`` before the first token; the
        resume admission re-plans with the already-generated tokens
        moved into the feed, so the page watermark is unchanged)."""
        return self.req.max_new_tokens - len(self.tokens)

    def cache_fill(self) -> int:
        """Host mirror of the device cache fill for a LIVE slot — THE
        single fill-accounting path (ISSUE 7 satellite: retirement, the
        tile-skip counter, COW write positions and the kv gauges all
        read this; two drifting copies would silently corrupt tile
        skipping). Prefill cached the prompt; each decode tick appends
        ONE token; the newest sampled token is NOT yet written — so the
        fill is ``prompt + generated - 1``, and the next decode append
        lands exactly here."""
        return len(self.req.prompt) + len(self.tokens) - 1


class Server:
    """The continuous-batching loop around one :class:`~mpit_tpu.serve.Engine`.

    Host-side only: slot bookkeeping, the request queue, retirement and
    telemetry. ``submit()`` enqueues; ``run()`` drives admit/decode
    ticks until the queue and all slots drain (or ``max_ticks``);
    ``run_timed()`` drives an open-loop arrival trace by its clock.

    ``stream`` (a :class:`mpit_tpu.obs.stream.StreamRegistry`) receives
    the rolling-window feed — ``request_ttft`` / ``request_latency`` /
    ``queue_wait`` histograms, ``serve_arrivals`` / ``serve_completed``
    / ``serve_tokens`` / ``serve_shed`` rates, ``queue_depth`` /
    ``slot_occupancy`` gauges; ``slo`` (a
    :class:`mpit_tpu.obs.slo.SLOMonitor` over the same registry) is
    evaluated once per tick. ``max_queue`` bounds the host queue:
    arrivals beyond it are SHED (recorded, not raised — open-loop
    traffic does not stop because the server is full).
    """

    def __init__(self, engine, *, sentinel=None, stream=None, slo=None,
                 max_queue=None, policy=None, ledger=None,
                 worker_id="", role=""):
        self.engine = engine
        self.sentinel = sentinel
        self.policy = policy
        # Fleet identity (ISSUE 19): a stable stamp on stats() and the
        # memory verdict so fleet-merged stats attribute bytes/tokens
        # per worker, not per process-anonymous engine. Standalone
        # servers report the explicit singleton identity.
        self.worker_id = worker_id or "single"
        self.role = role or "standalone"
        # Request lifecycle ledger (ISSUE 16): per-request causal events
        # at every decision seam, tail-exemplar retention, why-slow
        # attribution. ``None`` skips even the guard-site calls — the
        # ledger-disabled arm of the overhead acceptance bar.
        self._ledger = ledger
        if ledger is not None and sentinel is not None:
            # Breach/anomaly joinability (ISSUE 16 satellite): the
            # sentinel's note fan-out pins the in-flight request set at
            # detection time. Chain, don't clobber — a caller-installed
            # callback keeps firing.
            prev = sentinel.on_note

            def _pin(record, _prev=prev, _ledger=ledger):
                if _prev is not None:
                    _prev(record)
                _ledger.pin_inflight(
                    record.get("kind", "anomaly"), step=record.get("step")
                )

            sentinel.on_note = _pin
        if policy is not None and stream is None:
            # The policy's projected-TTFT estimator reads rolling
            # prefill/decode tick windows — when the caller didn't wire
            # a registry, a private one keeps admission evidence-based
            # instead of silently disabled.
            from mpit_tpu.obs.stream import StreamRegistry

            stream = StreamRegistry()
        self.stream = stream
        if policy is not None:
            policy.bind_registry(stream)
        self.slo = slo
        if slo is not None and stream is None:
            raise ValueError(
                "Server(slo=...) needs the stream registry the monitor "
                "evaluates over — pass stream=slo.registry"
            )
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        # The attention mode + sampler actually executing — stamped on
        # every prefill/decode span so the flight recorder / sentinel can
        # attribute a serve-path regression to a kernel fallback (ISSUE 5
        # obs satellite). Both labels matter: off-TPU "kernel" mode runs
        # reference ATTENTION but keeps the blocked SAMPLER, so
        # attention=reference alone does not identify the PR 4 path.
        self._attn_mode = getattr(
            engine, "decode_attention_mode", "reference"
        )
        self._sampler = getattr(engine, "decode_sampler", "dense")
        # kv_dtype rides decode/prefill spans via the attention= idiom
        # (ISSUE 15 satellite) — but only when the engine's wire dtype
        # was EXPLICITLY chosen: default engines' spans stay
        # byte-identical to HEAD, like grad_sync='s unlabeled psum.
        self._kv_attrs = (
            {"kv_dtype": engine.kv_dtype}
            if getattr(engine, "kv_dtype_explicit", False)
            else {}
        )
        # weights_dtype rides the same spans under the same rule
        # (ISSUE 17): the int8 weight store halves the decode sweep, so
        # a why-slow trace must say which wire the tick paid for — but
        # only explicitly-chosen engines get the label.
        if getattr(engine, "weights_dtype_explicit", False):
            self._kv_attrs = dict(
                self._kv_attrs, weights_dtype=engine.weights_dtype
            )
        self._paged = bool(getattr(engine, "paged", False))
        # Speculative decoding (ISSUE 13): spec_k > 0 swaps the decode
        # tick for draft-then-verify; the accumulators feed stats()'s
        # accepted_tokens_per_tick / draft_acceptance_rate (what the
        # gpt2_serve record line carries).
        self._spec = int(getattr(engine, "spec_k", 0) or 0)
        self._spec_emitted = 0
        self._spec_active_ticks = 0
        self._spec_drafted = 0
        self._spec_accepted = 0
        # Compile + utilization sentinel rules (ISSUE 8): an unexpected
        # engine recompile and a sustained collapse of the decode HBM
        # rate both land in THIS server's sentinel report, next to the
        # tick-duration findings.
        watch = getattr(engine, "compile_watch", None)
        if watch is not None and sentinel is not None:
            watch.sentinel = sentinel
        self._util_watch = (
            obs.roofline.UtilizationWatch(sentinel=sentinel)
            if sentinel is not None
            else None
        )
        self._decode_hbm_bytes = 0.0  # length-aware modeled bytes moved
        self.queue: deque[_Live] = deque()
        self.live: dict[int, _Live] = {}  # slot -> in-flight request
        # Paged engine: slots whose prompt is still being written, one
        # prefill_chunk slice per tick (chunked prefill — a 1024-token
        # admit can't head-of-line-block decode for every live slot).
        self.prefilling: dict[int, _Live] = {}
        self.free: list[int] = list(range(engine.slots))[::-1]  # pop() = slot 0 first
        self.completed: list[Completed] = []
        self.shed: list[Request] = []
        self.shed_causes: dict[str, int] = {}  # cause -> count (ISSUE 12)
        self.tick = 0
        self.admissions = 0
        self._occupancy_sum = 0.0
        self._kv_occ_sum = 0.0
        self._kv_occ_peak = 0.0
        self._pages_shared_peak = 0
        self._concurrency_peak = 0
        self._truncated = False  # a run stopped with work still pending
        self._pool_exhausted = False  # edge-trigger for the obs instant
        # The HBM memory ledger (ISSUE 18): the engine registered every
        # buffer at construction; the server reads headroom at every
        # admission verdict, tracks the run's peak/min watermarks, and
        # rolls the whole byte decomposition into stats()["memory"].
        self._memledger = getattr(engine, "memledger", None)
        self._held_peak = 0
        self._headroom_min_pct: float | None = None
        # Host KV tier (ISSUE 20): preemption victims park their pages
        # in host RAM and resume by restreaming instead of recomputing;
        # prefix entries migrate there instead of dying with their HBM
        # pages. Per-mode resume durations feed the p95
        # restream-vs-recompute comparison on the bench record line.
        self._host_tier = self._paged and getattr(engine, "host_pages", 0) > 0
        self._host_held_peak = 0
        self.resume_durations: dict[str, list] = {
            "restream": [], "recompute": [],
        }
        # Per-slot sampling-control arrays (host; refreshed on admit/retire).
        s = engine.slots
        self._temp = np.zeros((s,), np.float32)
        self._topk = np.zeros((s,), np.int32)

    # -- intake -------------------------------------------------------------
    def _span_attrs(self, req: Request) -> dict:
        """rid (+ tenant when set) for per-request span stamping —
        tenant is a string, so it also rolls up as a summary label."""
        return (
            {"rid": req.rid, "tenant": req.tenant}
            if req.tenant
            else {"rid": req.rid}
        )

    def submit(self, req: Request) -> bool:
        """Enqueue one request; returns False when it was SHED instead
        — ``max_queue`` bounded intake (``shed_queue_full``) or the
        policy's projected-TTFT admission verdict (``shed_admission``)
        (malformed requests still raise — shedding is a LOAD decision,
        validation is a caller bug)."""
        if not req.prompt:
            raise ValueError(f"request {req.rid!r}: empty prompt")
        if req.priority < 0:
            raise ValueError(
                f"request {req.rid!r}: priority must be >= 0 (0 = "
                f"highest tier), got {req.priority}"
            )
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.rid!r}: max_new_tokens must be >= 1 "
                f"(prefill always samples the first token), got "
                f"{req.max_new_tokens}"
            )
        if len(req.prompt) > self.engine.prefill_len:
            raise ValueError(
                f"request {req.rid!r}: prompt length {len(req.prompt)} > "
                f"engine prefill_len {self.engine.prefill_len}"
            )
        if len(req.prompt) + req.max_new_tokens > self.engine.max_len:
            raise ValueError(
                f"request {req.rid!r}: prompt + max_new_tokens "
                f"({len(req.prompt)} + {req.max_new_tokens}) exceeds the "
                f"engine's max_len {self.engine.max_len}"
            )
        if self._spec and not self._paged:
            # The dense verify writes k+1 rows at the current fill via
            # dynamic_update_slice, whose start CLAMPS at the buffer
            # edge — without headroom the window would shift backwards
            # and silently corrupt earlier rows inside the jitted step.
            # (The paged engine needs none: rows past a slot's mapped
            # pages are scatter-DROPPED.) Raise the precise error here,
            # at submit (ISSUE 13 satellite).
            need = len(req.prompt) + req.max_new_tokens + self._spec - 1
            if need > self.engine.max_len:
                raise ValueError(
                    f"request {req.rid!r}: speculative decode (spec_k="
                    f"{self._spec}) writes draft rows past the fill — "
                    f"prompt + max_new_tokens + spec_k - 1 = {need} "
                    f"exceeds the dense cache's max_len "
                    f"{self.engine.max_len}; shrink the request, lower "
                    f"spec_k, or grow max_len"
                )
        if self._paged:
            # A request the POOL could never hold is a caller bug, like
            # the max_len checks above — raise at submit, not when the
            # admit loop discovers it can never stop waiting. (The
            # per-slot virtual capacity is already covered: prompt +
            # max_new_tokens <= max_len = pages_per_slot × page_size.)
            alloc = self.engine.allocator
            need = alloc.pages_for(len(req.prompt), req.max_new_tokens)
            if need > alloc.num_pages:
                raise ValueError(
                    f"request {req.rid!r}: needs {need} pages of "
                    f"{alloc.page_size} tokens but the pool holds only "
                    f"{alloc.num_pages}; shrink prompt + max_new_tokens "
                    f"or grow Engine(kv_pages=...)"
                )
        k_cap = getattr(self.engine, "sample_k_cap", None)
        if k_cap is not None and req.top_k > k_cap:
            raise ValueError(
                f"request {req.rid!r}: top_k {req.top_k} exceeds the "
                f"blocked sampler's candidate buffer (sample_k_cap="
                f"{k_cap}); raise Engine(sample_k_cap=...) or use "
                f"top_k=0 (full vocab)"
            )
        if self.stream is not None:
            # Arrivals count BEFORE the shed decision: the shed-rate
            # SLO is shed/arrivals, so both sides of the ratio must see
            # every request that showed up.
            self.stream.inc("serve_arrivals")
        if self._ledger is not None:
            # The ledger opens at intake (post-validation): a SHED
            # request still gets its enqueue + verdict events — the
            # verdict is exactly what why-slow forensics needs.
            self._ledger.begin(
                req.rid, priority=req.priority, tenant=req.tenant,
                prompt_len=len(req.prompt), max_new=req.max_new_tokens,
            )
        # Two distinct shed causes (ISSUE 12 satellite) — bounded intake
        # vs the policy's projected-TTFT verdict — kept apart in the
        # cause-suffixed counters/instants/stats so breach forensics can
        # tell "queue physically full" from "queueing would only
        # manufacture a guaranteed SLO miss". ``serve_shed`` stays the
        # TOTAL: the shed-rate SLO numerator covers both causes.
        cause = None
        if self.max_queue is not None and self._qdepth() >= self.max_queue:
            cause = "queue_full"
        elif self.policy is not None:
            if self.policy.should_shed(req):
                cause = "admission"
                self.policy.shed_admission += 1
            if self._ledger is not None:
                # The admission verdict WITH the projection inputs that
                # produced it (ISSUE 16 tentpole) — the policy records
                # them in ``last_admission`` precisely so a later "the
                # projection lied" forensic can replay the arithmetic.
                self._ledger.event(
                    req.rid, "admission", **self.policy.last_admission
                )
        # Stable reason names for the instant/ledger (ISSUE 16
        # satellite): intake bound vs projection verdict, spelled out.
        reason = {
            "queue_full": "queue_full",
            "admission": "admission_projection",
        }.get(cause)
        if cause is not None:
            self.shed.append(req)
            self.shed_causes[cause] = self.shed_causes.get(cause, 0) + 1
            obs.counter("serve_shed")
            obs.counter(f"serve_shed_{cause}")
            # The headroom numbers at the refusal (ISSUE 18): a shed
            # verdict annotated with the bytes that were (not)
            # available when it was made — the causal event grows the
            # memory dimension the way ISSUE 16 grew the projection one.
            headroom = self._kv_headroom()
            obs.instant("request_shed", cause=cause, reason=reason,
                        queue_depth=self._qdepth(), **headroom,
                        **self._span_attrs(req))
            if self.stream is not None:
                self.stream.inc("serve_shed")
                self.stream.inc(f"serve_shed_{cause}")
            if self._ledger is not None:
                self._ledger.event(
                    req.rid, "shed", reason=reason,
                    queue_depth=self._qdepth(), **headroom,
                )
                self._ledger.retire(req.rid, status="shed", reason=reason)
            return False
        self._enqueue(_Live(req, time.perf_counter()))
        return True

    # -- queue plumbing (FIFO deque vs policy tier) --------------------------
    def _enqueue(self, live: _Live) -> None:
        if self.policy is not None:
            self.policy.enqueue(live)
        else:
            self.queue.append(live)

    def _qdepth(self) -> int:
        return (
            self.policy.pending()
            if self.policy is not None
            else len(self.queue)
        )

    def _next_queued(self) -> _Live | None:
        """Pop the next request to admit — FIFO order, or the policy's
        tier-then-deficit-round-robin choice."""
        if self.policy is not None:
            return self.policy.next()
        return self.queue.popleft() if self.queue else None

    def _restore_queued(self, live: _Live) -> None:
        """Undo one pop (the admission attempt found no pages): back to
        the queue head, order preserved (the policy also refunds the
        spent DRR credit)."""
        if self.policy is not None:
            self.policy.restore(live)
        else:
            self.queue.appendleft(live)

    # -- the loop -----------------------------------------------------------
    def _admit(self) -> None:
        """Move queued requests into free slots and start their
        prefill: dense = one batched whole-prompt call; paged = map
        pages and enter the per-tick chunk pipeline."""
        if self._paged:
            self._admit_paged()
        else:
            self._admit_dense()

    def _admit_paged(self) -> None:
        """Paged admission (ISSUE 7): grant the next queued request
        (FIFO head, or the policy's tier/DRR choice) a free slot AND
        its whole page requirement (fresh pages + shared-prefix
        mappings + COW reserve, all-or-nothing in the allocator) or
        stop. Stopping on the first request that doesn't fit keeps
        admission fair: a stream of small requests cannot starve a big
        one indefinitely. Admitted requests enter ``prefilling``;
        :meth:`_prefill_chunk_tick` feeds their prompt
        ``prefill_chunk`` tokens per tick.

        With a policy (ISSUE 12), a capacity miss — no free slot, or no
        pages for the chosen request — may PREEMPT instead of stopping:
        when the best queued tier's head is projected to miss its TTFT
        target, a lower-tier live generation is parked (pages freed,
        tokens kept host-side) and the loop retries. Each preemption
        frees one victim; termination is bounded by the live set and
        per-request ``max_preemptions``."""
        alloc = self.engine.allocator
        now = time.perf_counter()
        while True:
            if not self.free:
                if not self._try_preempt(now):
                    break
                continue  # a slot (and its victim's pages) just freed
            live = self._next_queued()
            if live is None:
                break
            slot = self.free[-1]
            feed = live.feed_tokens()
            plan = alloc.admit(
                slot, feed, live.remaining_new(),
                owner=live.req.rid, tenant=live.req.tenant or None,
                tick=self.tick,
            )
            if plan is None:
                # Pool full RIGHT NOW (nothing was taken) — back to the
                # queue head; retry after a retirement (or a preemption)
                # frees pages. Instant only on the TRANSITION into
                # exhaustion: a sustained overload would otherwise write
                # one instant per tick into the Recorder's bounded
                # buffer, evicting the spans the percentiles and the
                # obs diff gate read.
                self._restore_queued(live)
                if self._try_preempt(now):
                    continue  # freed pages; the restored head retries
                if self._ledger is not None:
                    # The refused admit's causal event carries the
                    # headroom numbers that refused it (ISSUE 18).
                    self._ledger.event(
                        live.req.rid, "admit_blocked", tick=self.tick,
                        need_pages=alloc.pages_for(
                            len(feed), live.remaining_new()
                        ),
                        free_pages=alloc.free_pages,
                        **self._kv_headroom(),
                    )
                if not self._pool_exhausted:
                    self._pool_exhausted = True
                    # Exhaustion forensics (ISSUE 18 tentpole b): the
                    # ranked top-holders table — who holds the pool the
                    # refused head needed — as a structured instant,
                    # retained on the ledger for the end-of-run
                    # snapshot and the `obs capacity` CLI.
                    dump = self._exhaustion_dump()
                    if self._memledger is not None:
                        self._memledger.note_exhaustion(dump)
                    obs.instant("kv_pool_exhausted", **dump)
                break
            self.free.pop()
            self._pool_exhausted = False  # an admit fit: episode over
            live.last_touch = self.tick
            # The write floor is the shared-token count; the forward
            # re-runs at least the LAST feed token (its logits seed
            # the next output token), so the feed base is capped one
            # below the feed end even on a full-feed prefix hit.
            live.floor = plan.shared_tokens
            live.base = min(plan.shared_tokens, len(feed) - 1)
            self._temp[slot] = live.req.temperature
            self._topk[slot] = live.req.top_k
            if plan.restream:
                # Host-tier prefix hit (ISSUE 20): restream the entry's
                # pages into the freshly granted device pages before the
                # first prefill chunk; the write floor then masks
                # re-writes below shared_tokens exactly as for an HBM
                # hit. The entry stays host-resident (release=False) —
                # it keeps serving hits until promotion frees it.
                for hp, dp in plan.restream:
                    self.engine.restore_page(
                        hp, dp, owner=live.req.rid, tick=self.tick
                    )
                obs.counter("kv_host_restreams", len(plan.restream))
            if self._ledger is not None:
                self._ledger.event(
                    live.req.rid, "slot_bind", slot=slot, tick=self.tick,
                    resumed=bool(live.tokens),
                    shared_tokens=plan.shared_tokens,
                    pages=plan.pages_granted,
                    restreamed_pages=len(plan.restream),
                )
            if live.tokens:
                # Resumed after a preemption: queue_wait/TTFT were
                # already delivered in the first stint — re-recording
                # them would double-count the request in the histograms.
                resume_mode = "recompute"
                if self._host_tier:
                    rec = alloc.peek_parked(live.req.rid)
                    if rec is not None:
                        if self._restream_parked(slot, live, plan, rec):
                            resume_mode = "restream"
                        alloc.take_parked(live.req.rid)
                live.resume_mode = resume_mode
                live.resume_t = now
                if self.policy is not None:
                    self.policy.resumes += 1
                obs.instant(
                    "request_resumed", generated=len(live.tokens),
                    **self._span_attrs(live.req),
                )
                if self._host_tier:
                    # The restream-vs-recompute OUTCOME instant
                    # (ISSUE 20): which rebuild path this resume took,
                    # joinable to the per-mode duration windows.
                    obs.instant(
                        "resume_" + resume_mode,
                        generated=len(live.tokens),
                        **self._span_attrs(live.req),
                    )
                if self._ledger is not None:
                    self._ledger.event(
                        live.req.rid, "preempt_resume", slot=slot,
                        tick=self.tick, generated=len(live.tokens),
                        mode=resume_mode if self._host_tier else "recompute",
                    )
            else:
                obs.span_at(
                    "queue_wait", live.submit_t, now,
                    **self._span_attrs(live.req),
                )
                if self.stream is not None:
                    self.stream.observe("queue_wait", now - live.submit_t)
            self.prefilling[slot] = live
            self.admissions += 1

    # -- preemption (ISSUE 12, paged engines only) ---------------------------
    def _try_preempt(self, now: float) -> bool:
        """Park one lower-tier live generation when the policy says the
        best queued tier's head would otherwise miss its TTFT target.
        Returns True when a victim was evicted (a slot + its pages are
        now free)."""
        if self.policy is None or not self._paged:
            return False
        priority = self.policy.wants_preemption(now)
        if priority is None:
            return False
        victim = self.policy.pick_victim(self.live, priority)
        if victim is None:
            return False
        self._preempt(victim, for_tier=priority)
        return True

    def _preempt(self, slot: int, *, for_tier: int | None = None) -> None:
        """Evict ``slot``'s live request: free its pages back to the
        allocator (sole-owner pages return to the free list, shared
        pages drop a refcount — exactly what retirement would free, the
        pool-accounting pin), park the request host-side with its
        generated-so-far tokens as the resume feed, and re-queue it at
        the FRONT of its own tier. The resume path is the normal
        chunked prefill over ``prompt + tokens`` — its final row
        recomputes the displaced decode tick, so the resumed greedy
        output bit-matches the un-preempted one (test-pinned)."""
        live = self.live.pop(slot)
        alloc = self.engine.allocator
        owned, shared = alloc.slot_page_stats(slot)
        spilled_pages = 0
        if self._host_tier:
            # ISSUE 20: park the victim's filled rows in host RAM
            # BEFORE the pages recycle — the spill gathers dispatch
            # async (the device buffers they read stay pinned even if
            # the very next admit rewrites the pages) and land at the
            # next tick boundary. All-or-nothing: an undersized host
            # tier parks nothing and resume recomputes, as before
            # tiering. Entries dying with the slot migrate too.
            planned = alloc.park_pages(
                live.req.rid, slot, live.cache_fill()
            )
            if planned is not None:
                copies, evicted = planned
                for hp in evicted:
                    self.engine.host_free(hp, kind="host_evict")
                for dp, hp in copies:
                    self.engine.spill_page(
                        dp, hp, owner=live.req.rid, tick=self.tick
                    )
                spilled_pages = len(copies)
            self._spill_dying_prefixes(slot, owner=live.req.rid)
        alloc.free_slot(slot)
        self.free.append(slot)
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        live.preempts += 1
        live.feed = list(live.req.prompt) + [int(t) for t in live.tokens]
        live.base = 0
        live.floor = 0
        live.park_tick = self.tick
        if self._memledger is not None:
            # Parked = cold by definition: the owner stays on the
            # recency index (state flips to "parked") so the eviction
            # ranking can surface it, coldest first (ISSUE 18).
            self._memledger.touch(
                live.req.rid, tick=self.tick,
                tenant=live.req.tenant or None, state="parked",
            )
        obs.counter("serve_preemptions")
        # The displacing rid (ISSUE 16): the head whose projected TTFT
        # miss justified this eviction — recorded by wants_preemption,
        # "" when the park came from a direct _preempt call.
        for_rid = (
            getattr(self.policy, "last_preemption_for", "") or ""
            if for_tier is not None
            else ""
        )
        obs.instant(
            "request_preempted",
            tier=live.req.priority,
            for_tier=for_tier if for_tier is not None else -1,
            generated=len(live.tokens),
            pages_freed=owned,
            pages_unshared=shared,
            pages_spilled=spilled_pages,
            **self._span_attrs(live.req),
        )
        if self._ledger is not None:
            self._ledger.event(
                live.req.rid, "preempt_park", tick=self.tick,
                tier=live.req.priority,
                for_tier=for_tier if for_tier is not None else -1,
                for_rid=for_rid, generated=len(live.tokens),
                pages_freed=owned,
            )
        if self.stream is not None:
            self.stream.inc("serve_preemptions")
        if self.policy is not None:
            self.policy.preemptions += 1
            self.policy.requeue_front(live)
        else:
            # Direct preemption on a policy-less server (tests, manual
            # eviction): FIFO resume order, front of the plain queue.
            self.queue.appendleft(live)

    def _spill_dying_prefixes(self, slot: int, *, owner=None) -> None:
        """Migrate prefix entries that would die with ``slot``'s pages
        into the host tier (ISSUE 20) — call immediately BEFORE
        ``free_slot``. Best-effort and all-or-nothing: when the host
        tier cannot hold the migration, the entries die exactly as
        before tiering."""
        if not self._host_tier:
            return
        copies, evicted = self.engine.allocator.spill_prefix_on_free(slot)
        for hp in evicted:
            self.engine.host_free(hp, kind="host_evict")
        for dp, hp in copies:
            self.engine.spill_page(dp, hp, owner=owner, tick=self.tick)
        if copies:
            obs.counter("kv_prefix_spills", len(copies))

    def _restream_parked(self, slot: int, live: _Live, plan, rec) -> bool:
        """Rebuild a resumed victim's cache rows ``[shared, fill)`` from
        its parked host pages instead of re-prefilling the feed
        (ISSUE 20). Rows below the admission's shared floor are already
        on device (prefix hit — possibly itself a restream); the
        boundary page COWs out first when still shared, and every
        restored page is written WHOLE (parked rows below the floor are
        bit-identical to the resident ones — K/V is a deterministic
        function of tokens and positions — and junk rows past the fill
        stay mask-hidden, exactly as after a normal prefill). On
        success the feed base jumps to the fill watermark, so the next
        prefill chunk is the single displaced decode row: the
        recompute path's bit-match discipline, minus the recompute.
        Returns False when the prefix hit already covers every parked
        row (payloads dropped unused)."""
        eng = self.engine
        alloc = eng.allocator
        ps = alloc.page_size
        s = plan.shared_tokens
        fill = rec.fill
        rid = live.req.rid
        if s >= fill:
            for hp in rec.host_pages:
                eng.host_free(hp, kind="restream_unused", owner=rid)
            return False
        if s % ps:
            # The boundary page holds shared rows below ``s``; a
            # whole-page restore over a still-shared page would corrupt
            # the other readers — COW it out first (admission reserved
            # the free page, the same guarantee a prefill write gets).
            pair = alloc.cow_before_write(slot, s)
            if pair is not None:
                eng.copy_page(*pair)
                obs.counter("kv_cow_copies")
                if self._ledger is not None:
                    self._ledger.event(
                        rid, "cow_copy", tick=self.tick,
                        src=pair[0], dst=pair[1], phase="restream",
                    )
        bt = alloc.block_tables[slot]
        for pi in range(s // ps, (fill - 1) // ps + 1):
            eng.restore_page(
                int(rec.host_pages[pi]), int(bt[pi]),
                release=True, kind="restream", owner=rid, tick=self.tick,
            )
        for pi in range(0, s // ps):
            # Fully below the shared floor: the device prefix hit
            # already provides these rows — drop the payloads.
            eng.host_free(
                int(rec.host_pages[pi]), kind="restream_unused", owner=rid
            )
        live.base = fill
        live.floor = fill
        return True

    def _prefill_chunk_tick(self) -> None:
        """Advance every prefilling slot by ONE prompt chunk (one
        batched call). Slots whose final prompt token rides this chunk
        sample their first output token, register their prompt in the
        prefix index (only now — an index entry must never advertise
        K/V not yet on the device) and go live."""
        if not self.prefilling:
            return
        eng = self.engine
        alloc = eng.allocator
        s, w = eng.slots, eng.prefill_chunk
        tokens = np.zeros((s, w), np.int32)
        base = np.zeros((s,), np.int32)
        chunk_lens = np.zeros((s,), np.int32)
        floor = np.zeros((s,), np.int32)
        sample_mask = np.zeros((s,), bool)
        finishing: list[tuple[int, _Live]] = []
        now = time.perf_counter()
        for slot, live in self.prefilling.items():
            p = live.feed_tokens()
            n = min(w, len(p) - live.base)
            # First write of this chunk: at the floor on a partial-page
            # prefix hit, else at the feed base. A write landing in a
            # still-shared page copies it out first (device page copy);
            # the allocator's admission reserve guarantees the free page.
            first_write = max(live.base, live.floor)
            if first_write < live.base + n:
                pair = alloc.cow_before_write(slot, first_write)
                if pair is not None:
                    eng.copy_page(*pair)
                    obs.counter("kv_cow_copies")
                    if self._ledger is not None:
                        self._ledger.event(
                            live.req.rid, "cow_copy", tick=self.tick,
                            src=pair[0], dst=pair[1], phase="prefill",
                        )
            tokens[slot, :n] = p[live.base : live.base + n]
            base[slot] = live.base
            chunk_lens[slot] = n
            floor[slot] = live.floor
            if live.base + n == len(p):
                sample_mask[slot] = True
                finishing.append((slot, live))
        with obs.span(
            "prefill",
            admitted=len(finishing),
            chunks=int((chunk_lens > 0).sum()),
            attention=self._attn_mode,
            sampler=self._sampler,
            rids=[live.req.rid for live in self.prefilling.values()],
            **self._kv_attrs,
        ):
            first = eng.prefill_paged(
                tokens, base, chunk_lens, floor, sample_mask,
                self._temp, self._topk,
            )
        t_first = time.perf_counter()
        if self.sentinel is not None:
            self.sentinel.observe_phases(self.tick, prefill=t_first - now)
        if self.stream is not None:
            # The policy projector's per-chunk cost basis (ISSUE 12).
            self.stream.observe("prefill_tick", t_first - now)
        if self._ledger is not None:
            # One event per slot that actually advanced — the chunk
            # length and the tick wall feed prefill_compute_s in the
            # why-slow attribution.
            for slot, live in self.prefilling.items():
                n = int(chunk_lens[slot])
                if n:
                    self._ledger.event(
                        live.req.rid, "prefill_chunk", tick=self.tick,
                        chunk=n, dur_s=t_first - now, t=t_first,
                    )
        for slot in self.prefilling:
            live = self.prefilling[slot]
            live.base += int(chunk_lens[slot])
            if chunk_lens[slot]:
                live.last_touch = self.tick
        for slot, live in finishing:
            del self.prefilling[slot]
            promoted = alloc.register_prefix(
                slot, live.feed_tokens(), tick=self.tick
            )
            for hp in promoted:
                # ISSUE 20: the prompt's prefix is resident on device
                # again — the allocator promoted its host entries, and
                # the freed host seats drop their payloads here.
                self.engine.host_free(
                    hp, kind="promote", owner=live.req.rid
                )
            if live.tokens:
                # Resumed after a preemption: this chunk's sampled
                # token IS the decode step the eviction displaced —
                # append it; TTFT was already delivered before the park.
                live.tokens.append(int(first[slot]))
                if live.resume_mode:
                    # Close the resume: admission → first post-resume
                    # token, by rebuild mode (ISSUE 20 — the p95
                    # restream-vs-recompute comparison's sample).
                    dur = t_first - live.resume_t
                    self.resume_durations.setdefault(
                        live.resume_mode, []
                    ).append(dur)
                    if self.stream is not None:
                        self.stream.observe(
                            f"resume_{live.resume_mode}", dur
                        )
                    live.resume_mode = ""
            else:
                live.first_token_t = t_first
                live.tokens = [int(first[slot])]
                self._record_ttft(live, t_first)
            self.live[slot] = live
            self._maybe_retire(slot, t_first)

    def _record_ttft(self, live: _Live, t_first: float) -> None:
        """First-token bookkeeping: the request_ttft span + rolling
        windows, plus the per-tier series (``request_ttft_tier<p>`` —
        what a tier-scoped SLO target reads) when tiers are in play and
        the per-tenant series behind ``stats()``'s tenant roll-up."""
        req = live.req
        obs.span_at(
            "request_ttft", live.submit_t, t_first,
            **self._span_attrs(req),
        )
        if self.stream is None:
            return
        ttft = t_first - live.submit_t
        self.stream.observe("request_ttft", ttft)
        if self.policy is not None or req.priority or req.ttft_target_s > 0:
            self.stream.observe(f"request_ttft_tier{req.priority}", ttft)
        if req.tenant:
            self.stream.observe(f"request_ttft_tenant:{req.tenant}", ttft)

    def _admit_dense(self) -> None:
        """Move queued requests into free slots and prefill them (one
        batched call however many were admitted this tick) — FIFO
        order, or the policy's tier/DRR order (no preemption on the
        dense engine: a slot has no pages to free)."""
        if not self._qdepth() or not self.free:
            return
        s, plen = self.engine.slots, self.engine.prefill_len
        tokens = np.zeros((s, plen), np.int32)
        lens = np.ones((s,), np.int32)
        admit = np.zeros((s,), bool)
        batch: list[tuple[int, _Live]] = []
        now = time.perf_counter()
        while self.free:
            live = self._next_queued()
            if live is None:
                break
            slot = self.free.pop()
            p = live.req.prompt
            tokens[slot, : len(p)] = p
            lens[slot] = len(p)
            admit[slot] = True
            self._temp[slot] = live.req.temperature
            self._topk[slot] = live.req.top_k
            live.last_touch = self.tick
            if self._memledger is not None:
                # Dense capacity is slot-granular (ISSUE 18): one slot
                # reservation granted per admission, freed at retire —
                # the dense twin of the allocator's page grants.
                self._memledger.grant(
                    "kv_slots", self.engine.slot_bytes,
                    owner=live.req.rid, tenant=live.req.tenant or None,
                    tick=self.tick, kind="admit",
                )
            if self._ledger is not None:
                self._ledger.event(
                    live.req.rid, "slot_bind", slot=slot, tick=self.tick,
                    resumed=False, t=now,
                )
            obs.span_at(
                "queue_wait", live.submit_t, now,
                **self._span_attrs(live.req),
            )
            if self.stream is not None:
                self.stream.observe("queue_wait", now - live.submit_t)
            batch.append((slot, live))
        with obs.span(
            "prefill", admitted=len(batch), attention=self._attn_mode,
            sampler=self._sampler,
            # The admitted rids, as a LIST (a non-string attr stays out
            # of the summary's label roll-up but lands in the trace
            # args) — one request's lifeline is filterable in Perfetto.
            rids=[live.req.rid for _, live in batch],
            **self._kv_attrs,
        ):
            first = self.engine.prefill(
                tokens, lens, admit, self._temp, self._topk
            )
        t_first = time.perf_counter()
        self.admissions += len(batch)
        if self.sentinel is not None:
            self.sentinel.observe_phases(
                self.tick, prefill=t_first - now
            )
        if self.stream is not None:
            self.stream.observe("prefill_tick", t_first - now)
        if self._ledger is not None:
            # Dense prefill is one whole-prompt chunk; the shared batch
            # wall is each admitted request's prefill-compute share.
            for slot, live in batch:
                self._ledger.event(
                    live.req.rid, "prefill_chunk", tick=self.tick,
                    chunk=len(live.req.prompt), dur_s=t_first - now,
                    t=t_first,
                )
        for slot, live in batch:
            live.first_token_t = t_first
            live.tokens = [int(first[slot])]
            self._record_ttft(live, t_first)
            self.live[slot] = live
            self._maybe_retire(slot, t_first)

    def _maybe_retire(self, slot: int, now: float) -> None:
        """Retire ``slot`` if its newest token finished the request."""
        live = self.live[slot]
        req = live.req
        tok = live.tokens[-1]
        # The next decode would write at the fill position — at max_len
        # the slot must retire or it would overrun the buffer (dense) /
        # its mapped pages (paged).
        full = live.cache_fill() >= self.engine.max_len
        done = (
            (req.eos_id is not None and tok == req.eos_id)
            or len(live.tokens) >= req.max_new_tokens
            or full
        )
        if not done:
            return
        del self.live[slot]
        if self._paged:
            # Unmap the slot's pages: refcounts drop, sole-owner pages
            # return to the free list (recycled WITHOUT zeroing — the
            # mask defines validity), prefix-index entries whose pages
            # died are invalidated — unless the host tier catches them
            # first (ISSUE 20: a sole-reader prefix migrates instead of
            # dying, so the index survives HBM reclaim).
            self._spill_dying_prefixes(slot, owner=req.rid)
            self.engine.allocator.free_slot(slot)
        elif self._memledger is not None:
            self._memledger.free(
                "kv_slots", self.engine.slot_bytes,
                owner=req.rid, kind="retire",
            )
        if self._memledger is not None:
            self._memledger.forget(req.rid)
        self.free.append(slot)
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        obs.span_at(
            "request_latency", live.submit_t, now, **self._span_attrs(req)
        )
        obs.counter("serve_requests")
        if self.stream is not None:
            self.stream.observe("request_latency", now - live.submit_t)
            self.stream.inc("serve_completed")
        truncated = (
            full and tok != req.eos_id and len(live.tokens) < req.max_new_tokens
        )
        if self._ledger is not None:
            reason = (
                "eos"
                if req.eos_id is not None and tok == req.eos_id
                else (
                    "max_tokens"
                    if len(live.tokens) >= req.max_new_tokens
                    else "cache_full"
                )
            )
            self._ledger.event(
                req.rid, "retire", tick=self.tick, reason=reason,
                generated=len(live.tokens), t=now,
            )
            self._ledger.retire(
                req.rid, t=now,
                status="truncated" if truncated else "completed",
                reason=reason,
            )
        self.completed.append(
            Completed(
                rid=req.rid,
                prompt=list(req.prompt),
                tokens=list(live.tokens),
                submit_t=live.submit_t,
                first_token_t=live.first_token_t,
                finish_t=now,
                truncated=truncated,
                tenant=req.tenant,
            )
        )

    def _spec_tick(self) -> None:
        """One speculative decode tick (ISSUE 13): draft k tokens per
        live slot, verify all k+1 positions in ONE target pass, emit
        each slot's longest accepted prefix plus the replacement/bonus
        token — cache lengths advanced in-step by exactly the emitted
        count (the rollback). Spanned as ``decode`` with nested
        ``spec_draft`` / ``spec_verify`` spans (the ``attention=``
        idiom rides all three), so the flight recorder attributes
        draft vs verify work while the decode-phase roll-up — bench
        denominators, the sentinel — still covers the whole tick."""
        eng = self.engine
        k = self._spec
        active = np.zeros((eng.slots,), bool)
        budget = np.ones((eng.slots,), np.int32)
        eos = np.full((eng.slots,), -1, np.int32)
        for slot, live in self.live.items():
            active[slot] = True
            budget[slot] = live.remaining_new()
            if live.req.eos_id is not None:
                eos[slot] = live.req.eos_id
        if self._paged:
            # Every page the verify span [fill, fill+k] can write must
            # be privately owned BEFORE the step — the plain tick's COW
            # probe, once per page in the span. Only the shared-prefix
            # partial page can actually be shared, so at most one copy
            # runs; the rest are no-op refcount probes.
            ps = eng.page_size
            caps = eng.allocator.mapped_tokens()
            for slot, live in self.live.items():
                fill = live.cache_fill()
                last_pos = min(fill + k, int(caps[slot]) - 1)
                for page_idx in range(fill // ps, last_pos // ps + 1):
                    pair = eng.allocator.cow_before_write(
                        slot, max(fill, page_idx * ps)
                    )
                    if pair is not None:
                        eng.copy_page(*pair)
                        obs.counter("kv_cow_copies")
                        if self._ledger is not None:
                            self._ledger.event(
                                live.req.rid, "cow_copy", tick=self.tick,
                                src=pair[0], dst=pair[1], phase="spec",
                            )
        n_live = int(active.sum())
        rids = [live.req.rid for live in self.live.values()]
        t0 = time.perf_counter()
        with obs.span(
            "decode", active=n_live, attention=self._attn_mode,
            sampler=self._sampler, spec_k=k, rids=rids,
            **self._kv_attrs,
        ):
            with obs.span(
                "spec_draft", active=n_live, attention=self._attn_mode,
                sampler=self._sampler, rids=rids, **self._kv_attrs,
            ):
                eng.spec_draft(active, self._temp, self._topk)
            t1 = time.perf_counter()
            with obs.span(
                "spec_verify", active=n_live, attention=self._attn_mode,
                sampler=self._sampler, rids=rids, **self._kv_attrs,
            ):
                emit, n_emit, n_acc = eng.spec_verify(
                    active, self._temp, self._topk, budget, eos
                )
        now = time.perf_counter()
        if self.sentinel is not None:
            self.sentinel.observe_phases(self.tick, decode=now - t0)
        emitted = int(n_emit.sum())
        accepted = int(n_acc.sum())
        obs.counter("serve_tokens", float(emitted))
        obs.counter("spec_drafted_tokens", float(k * n_live))
        obs.counter("spec_accepted_tokens", float(accepted))
        self._spec_emitted += emitted
        self._spec_active_ticks += n_live
        self._spec_drafted += k * n_live
        self._spec_accepted += accepted
        if self.stream is not None:
            self.stream.inc("serve_tokens", float(emitted))
            self.stream.observe("decode_tick", now - t0)
            self.stream.observe("spec_draft_tick", t1 - t0)
            self.stream.observe("spec_verify_tick", now - t1)
            if n_live:
                # Tokens emitted per slot-tick (1.0 = plain decode) —
                # the throughput multiplier the record line carries —
                # and the fraction of drafted tokens the target kept.
                self.stream.observe(
                    "accepted_tokens_per_tick", emitted / n_live
                )
                self.stream.observe(
                    "draft_acceptance_rate", accepted / (k * n_live)
                )
        lens = np.asarray(
            [live.cache_fill() for live in self.live.values()]
        )
        if self._attn_mode == "kernel":
            # Same single-formula tile accounting as the plain tick,
            # at the verify's T = k+1 query width.
            bk = eng.decode_block_k
            total = eng.max_len // bk
            visited = num_kv_blocks(lens, k + 1, eng.max_len, bk)
            n_free = eng.slots - lens.size
            obs.counter(
                "decode_blocks_skipped",
                float(total * eng.slots - int(visited.sum()) - n_free),
            )
        ach = eng.decode_achieved_hbm_bytes(lens, t_q=k + 1)
        if ach is not None:
            self._decode_hbm_bytes += ach
            obs.roofline.work("spec_verify", hbm_bytes=ach)
            costs = getattr(eng, "roofline_costs", None) or {}
            flops = costs.get("spec_verify", {}).get("flops", 0.0)
            if self.stream is not None:
                self.stream.inc("decode_hbm_bytes", ach)
                if flops:
                    self.stream.inc("decode_flops", flops)
            if self._util_watch is not None and now > t1:
                # The modeled bytes cover the VERIFY pass only, so the
                # rate divides by the verify wall (t1 = draft/verify
                # boundary) — over the whole tick a slow draft would
                # structurally depress the rate and trip the sustained-
                # collapse watch on a healthy engine.
                self._util_watch.observe(
                    "decode_hbm_gbps", self.tick, ach / (now - t1) / 1e9
                )
        if self._ledger is not None:
            # Per-slot draft/accept accounting (ISSUE 16): the rollback
            # streak a spec-heavy slow request suffered is only visible
            # per request, never in the aggregate acceptance rate.
            for slot, live in self.live.items():
                self._ledger.event(
                    live.req.rid, "spec_tick", tick=self.tick,
                    dur_s=now - t0, drafted=k, t=now,
                    accepted=int(n_acc[slot]), emitted=int(n_emit[slot]),
                )
        for slot in list(self.live):
            n = int(n_emit[slot])
            self.live[slot].tokens.extend(
                int(t) for t in emit[slot, :n]
            )
            self.live[slot].last_touch = self.tick
            self._maybe_retire(slot, now)

    def _decode_tick(self) -> None:
        if self._spec:
            self._spec_tick()
            return
        active = np.zeros((self.engine.slots,), bool)
        for slot in self.live:
            active[slot] = True
        if self._paged:
            # This tick appends one K/V row per live slot at its fill
            # position — a slot whose fill still lands in a SHARED page
            # (full-prompt prefix reuse of a partial last page) must
            # copy it out first; later ticks find the page private and
            # this is a no-op refcount probe.
            for slot, live in self.live.items():
                pair = self.engine.allocator.cow_before_write(
                    slot, live.cache_fill()
                )
                if pair is not None:
                    self.engine.copy_page(*pair)
                    obs.counter("kv_cow_copies")
                    if self._ledger is not None:
                        self._ledger.event(
                            live.req.rid, "cow_copy", tick=self.tick,
                            src=pair[0], dst=pair[1], phase="decode",
                        )
        t0 = time.perf_counter()
        with obs.span(
            "decode", active=int(active.sum()), attention=self._attn_mode,
            sampler=self._sampler,
            rids=[live.req.rid for live in self.live.values()],
            **self._kv_attrs,
        ):
            toks = self.engine.decode(active, self._temp, self._topk)
        now = time.perf_counter()
        if self.sentinel is not None:
            self.sentinel.observe_phases(self.tick, decode=now - t0)
        obs.counter("serve_tokens", float(active.sum()))
        if self.stream is not None:
            self.stream.inc("serve_tokens", float(active.sum()))
            # The policy projector's decode-tick term (ISSUE 12).
            self.stream.observe("decode_tick", now - t0)
        if self._ledger is not None:
            # Decode-tick MEMBERSHIP: the tick wall is every resident
            # request's latency cost (the tick is shared; the slot is
            # occupied for all of it) — decode_compute_share_s.
            n_live = int(active.sum())
            for live in self.live.values():
                self._ledger.event(
                    live.req.rid, "decode_tick", tick=self.tick,
                    dur_s=now - t0, active=n_live, t=now,
                )
        lens = np.asarray(
            [live.cache_fill() for live in self.live.values()]
        )
        if self._attn_mode == "kernel":
            # Cache tiles the length-aware kernel skipped this tick —
            # ONE formula, num_kv_blocks, shared with the kernel's own
            # in-kernel bound (pinned against it in
            # tests/test_decode_attention.py), so the counter cannot
            # drift from what the kernel actually visits. A serve
            # regression with this counter flat at 0 = kernel fallback.
            # The decode step runs over ALL slots: free slots' lengths
            # are clamped to 0 in-step, so each one visits exactly 1
            # tile — counted here too, or the counter would understate
            # the skipping the clamp buys.
            bk = self.engine.decode_block_k
            total = self.engine.max_len // bk
            visited = num_kv_blocks(lens, 1, self.engine.max_len, bk)
            n_free = self.engine.slots - lens.size
            obs.counter(
                "decode_blocks_skipped",
                float(
                    total * self.engine.slots
                    - int(visited.sum())
                    - n_free  # 1 visited tile per clamped free slot
                ),
            )
        # Length-aware achieved work (ISSUE 8): the honest HBM figure
        # for a tile-skipping kernel comes from the tiles it VISITS,
        # not the padded cost_analysis buffer — fed as explicit work so
        # the summary's decode utilization uses it, mirrored into the
        # rolling stream windows (the CLI's hbmbw=/mfu= fields) and the
        # sustained-collapse watch.
        ach = getattr(self.engine, "decode_achieved_hbm_bytes", None)
        ach = ach(lens) if ach is not None else None
        if ach is not None:
            self._decode_hbm_bytes += ach
            obs.roofline.work("decode", hbm_bytes=ach)
            costs = getattr(self.engine, "roofline_costs", None) or {}
            flops = costs.get("decode", {}).get("flops", 0.0)
            if self.stream is not None:
                self.stream.inc("decode_hbm_bytes", ach)
                if flops:
                    self.stream.inc("decode_flops", flops)
            if self._util_watch is not None and now > t0:
                self._util_watch.observe(
                    "decode_hbm_gbps", self.tick, ach / (now - t0) / 1e9
                )
        for slot in list(self.live):
            self.live[slot].tokens.append(int(toks[slot]))
            self.live[slot].last_touch = self.tick
            self._maybe_retire(slot, now)

    def _pending(self) -> bool:
        """Work outstanding: queued (FIFO deque or policy tiers),
        mid-prefill (paged chunking) or live — the loop-termination and
        truncation predicate."""
        return bool(self._qdepth() or self.prefilling or self.live)

    def _kv_gauges(self) -> None:
        """Cache-memory efficiency gauges (ISSUE 7 satellite):
        ``kv_tokens_cached`` = tokens actually held device-side (live
        fills + prefill progress — what a token-proportional cache pays
        for), plus pool occupancy and shared-page count on the paged
        engine. Recorder gauges AND the rolling stream windows."""
        kv_tokens = float(
            sum(l.cache_fill() for l in self.live.values())
            + sum(l.base for l in self.prefilling.values())
        )
        obs.gauge("kv_tokens_cached", kv_tokens)
        if self.stream is not None:
            self.stream.set_gauge("kv_tokens_cached", kv_tokens)
        self._memory_gauges(kv_tokens)
        if not self._paged:
            return
        alloc = self.engine.allocator
        occ = alloc.occupancy
        shared = alloc.pages_shared
        self._kv_occ_sum += occ
        self._kv_occ_peak = max(self._kv_occ_peak, occ)
        self._pages_shared_peak = max(self._pages_shared_peak, shared)
        obs.gauge("kv_pool_occupancy", occ)
        obs.gauge("prefix_pages_shared", float(shared))
        if self.stream is not None:
            self.stream.set_gauge("kv_pool_occupancy", occ)
            self.stream.set_gauge("prefix_pages_shared", float(shared))

    def _memory_gauges(self, kv_tokens: float) -> None:
        """Live headroom / watermark / fragmentation gauges (ISSUE 18
        tentpole a): total held bytes, the KV pool's held bytes and
        headroom, and internal fragmentation — granted page capacity
        not covered by cached tokens (tail rows of partially filled
        pages). Recorder gauges AND the rolling stream windows (the
        serve CLI's ``hbm=/held=/headroom=`` fields); the run's peak
        held and minimum headroom are tracked here, once per tick."""
        ml = self._memledger
        if ml is None:
            return
        held = ml.held()
        self._held_peak = max(self._held_peak, int(held))
        head = self._kv_headroom()
        gauges = {"hbm_held_bytes": float(held)}
        if self._host_tier:
            # Host-tier watermark, sampled per tick like the HBM peak
            # (ISSUE 20) — the ``host_held_peak_bytes`` the diff gate
            # compares must not depend on when stats() was last called.
            host_held = int(ml.held("kv_host_pages"))
            self._host_held_peak = max(self._host_held_peak, host_held)
            gauges["host_held_bytes"] = float(host_held)
        sub = "kv_pages" if self._paged else "kv_slots"
        kv_held = ml.held(sub) + (
            ml.held("kv_cow_reserve") if self._paged else 0.0
        )
        gauges["kv_held_bytes"] = float(kv_held)
        if "kv_headroom_pct" in head:
            pct = head["kv_headroom_pct"]
            self._headroom_min_pct = (
                pct
                if self._headroom_min_pct is None
                else min(self._headroom_min_pct, pct)
            )
            gauges["kv_headroom_pct"] = pct
        if self._paged:
            in_use = self.engine.allocator.pages_in_use
            granted_tokens = in_use * self.engine.page_size
            gauges["kv_frag_pct"] = (
                round(100.0 * (1.0 - kv_tokens / granted_tokens), 2)
                if granted_tokens
                else 0.0
            )
        for name, val in gauges.items():
            obs.gauge(name, val)
            if self.stream is not None:
                self.stream.set_gauge(name, val)

    def _kv_headroom(self) -> dict:
        """KV capacity headroom RIGHT NOW — the bytes an admission
        verdict had to work with (annotated onto sheds and blocked
        admits). Paged: free grantable pages × page bytes (COW reserve
        excluded — those bytes are promised). Dense: free slot
        reservations. Empty when the engine has no ledger."""
        ml = self._memledger
        if ml is None:
            return {}
        sub = "kv_pages" if self._paged else "kv_slots"
        cap = ml.capacity(sub)
        if not cap:
            return {}
        held = ml.held(sub) + (
            ml.held("kv_cow_reserve") if self._paged else 0.0
        )
        headroom = cap - held
        return {
            "kv_headroom_bytes": int(headroom),
            "kv_headroom_pct": round(100.0 * headroom / cap, 2),
            "hbm_held_bytes": int(ml.held()),
        }

    def _exhaustion_dump(self) -> dict:
        """The ranked top-holders table for a pool-exhaustion edge
        (ISSUE 18 tentpole b): per-request exclusive bytes (what
        evicting each would actually return), per-tenant totals, the
        subsystem decomposition, COW reserve, and the prefix-index
        health counts — everything a "why won't this admit" forensic
        needs, computed from allocator ground truth at the edge."""
        alloc = self.engine.allocator
        pb = self.engine.page_bytes
        holders = []
        for slot, live in list(self.live.items()) + list(
            self.prefilling.items()
        ):
            owned, shared = alloc.slot_page_stats(slot)
            holders.append({
                "rid": live.req.rid,
                "tenant": live.req.tenant or "",
                "bytes": int(owned * pb),
                "shared_pages": shared,
                "last_touch_tick": live.last_touch,
            })
        holders.sort(key=lambda e: (-e["bytes"], str(e["rid"])))
        tenants: dict[str, int] = {}
        for h in holders:
            tenants[h["tenant"]] = tenants.get(h["tenant"], 0) + h["bytes"]
        sole, dead = self._prefix_entry_counts()
        out = {
            "tick": self.tick,
            "free_pages": alloc.free_pages,
            "queued": self._qdepth(),
            "top_holders": holders[:8],
            "tenants": dict(
                sorted(tenants.items(), key=lambda kv: -kv[1])
            ),
            "cow_reserve_bytes": int(alloc.reserved * pb),
            "sole_reader_prefix_entries": sole,
            # 0 by construction (entries die with their pages) —
            # reported so a future allocator change that breaks the
            # invariant shows up as leaked dead entries, not silence.
            "dead_prefix_entries": dead,
        }
        if self._host_tier:
            # Host-tier pressure facts (ISSUE 20): the capacity verdict
            # names whether this exhaustion is HBM-only (host seats
            # still free — spills can relieve) or squeezes both tiers.
            out["host_free_pages"] = len(alloc.host_free)
            out["host_pages"] = alloc.host_pages
            out["host_parked_records"] = len(alloc._parked)
            out["host_resident_entries"] = alloc.host_resident_entries
        out["tier_pressure"] = (
            "both_tiers"
            if self._host_tier and not alloc.host_free
            else "hbm_only"
        )
        if self._memledger is not None:
            out["subsystems"] = self._memledger.decompose()
        out.update(self._kv_headroom())
        return out

    def _prefix_entry_counts(self) -> tuple[int, int]:
        """(sole-reader, dead) prefix-index entry counts: entries whose
        pages are all refcount 1 (only the registrant still maps them —
        reclaimable by retiring one idle slot) and entries citing a
        page at refcount 0 (impossible by construction; counted so a
        regression surfaces). Host-tier entries are excluded — their
        page ids name host seats, not refcounted device pages."""
        alloc = self.engine.allocator
        sole = dead = 0
        for entry in alloc._index.values():
            if entry.tier != "hbm":
                continue
            refs = [int(alloc.refcount[p]) for p in entry.pages]
            if any(r == 0 for r in refs):
                dead += 1
            elif all(r == 1 for r in refs):
                sole += 1
        return sole, dead

    def _run_tick(self) -> None:
        """One loop iteration: admit, prefill chunk (paged), gauges,
        decode, SLO evaluation."""
        if self._host_tier:
            # Land last tick's dispatched spills (ISSUE 20): the
            # device→host copies ran under the decode tick they were
            # dispatched with (the Prefetcher's two-stage overlap);
            # materializing here costs only the memcpy, never the wait.
            self.engine.drain_spills()
        self._admit()
        if self._paged:
            self._prefill_chunk_tick()
        busy = len(self.live) + len(self.prefilling)
        self._concurrency_peak = max(self._concurrency_peak, busy)
        occupancy = busy / self.engine.slots
        self._occupancy_sum += occupancy
        obs.gauge("slot_occupancy", occupancy)
        if self.stream is not None:
            self.stream.set_gauge("slot_occupancy", occupancy)
            self.stream.set_gauge("queue_depth", float(self._qdepth()))
        if self.policy is not None:
            # Per-tier backlog (ISSUE 12): one gauge per tier the run
            # has seen — zeros included, so an emptied tier reads 0,
            # not its last nonzero value.
            for tier, depth in self.policy.tier_depths().items():
                obs.gauge(f"queue_depth_tier{tier}", float(depth))
                if self.stream is not None:
                    self.stream.set_gauge(
                        f"queue_depth_tier{tier}", float(depth)
                    )
        self._kv_gauges()
        if self.live:
            self._decode_tick()
        if self.slo is not None:
            transitions = self.slo.evaluate(tick=self.tick)
            if (
                self._ledger is not None
                and getattr(self.slo, "sentinel", None) is None
            ):
                # No sentinel wired: pin the in-flight set from the
                # monitor's returned transitions directly (with a
                # sentinel the on_note chain installed in __init__
                # already did it — never both, or breaches double-pin).
                for tr in transitions:
                    if tr.get("event") == "slo_breach":
                        self._ledger.pin_inflight(
                            "slo_breach", step=self.tick
                        )
        self.tick += 1

    def run(self, *, max_ticks: int = 1_000_000) -> list[Completed]:
        """Drive admit/decode until everything submitted has completed
        (then return ALL completions so far, in finish order). Hitting
        ``max_ticks`` with work still queued/live sets the
        ``truncated`` flag ``stats()`` reports — partial completions
        must not read as a finished run."""
        # Each call is a fresh verdict: a prior max_ticks-capped run
        # (e.g. a staggered prime before more submits) must not latch
        # ``truncated`` onto a follow-up run that drains everything.
        self._truncated = False
        while self._pending() and self.tick < max_ticks:
            self._run_tick()
        if self._pending():
            self._truncated = True
        if self.slo is not None:
            self.slo.finish()
        return self.completed

    def run_timed(
        self,
        arrivals,
        *,
        duration: float | None = None,
        drain: bool = True,
        max_ticks: int = 1_000_000,
        on_tick=None,
    ) -> list[Completed]:
        """Open-loop drive: submit each :class:`~mpit_tpu.serve.loadgen.
        Arrival` when its clock (seconds from the call) comes due, tick
        the engine in between, and stop admitting at ``duration``
        seconds (``None`` = when the trace is exhausted).

        ``drain=True`` keeps ticking past the admission window until
        queued + live work finishes — every admitted request gets an
        answer (the CLI default). ``drain=False`` stops AT the window's
        end — the honest overload measurement: past saturation the
        queue grows without bound and a drain would never return; what
        completed inside the window is the result, and ``stats()``
        reports ``truncated`` for the rest. ``on_tick(server, now_s)``
        is called once per loop iteration (the CLI's live stats line).
        Requests shed by ``max_queue`` are counted, not raised.
        """
        arrivals = sorted(arrivals, key=lambda a: a.t)
        self._truncated = False  # fresh verdict, as in :meth:`run`
        t0 = time.perf_counter()
        i = 0
        end_t = math.inf if duration is None else duration
        while self.tick < max_ticks:
            now = time.perf_counter() - t0
            while i < len(arrivals) and arrivals[i].t <= min(now, end_t):
                self.submit(arrivals[i].request)
                i += 1
            pending_arrivals = i < len(arrivals) and arrivals[i].t < end_t
            if now >= end_t and not (drain and self._pending()):
                break
            if not pending_arrivals and not self._pending():
                if now >= end_t or i >= len(arrivals):
                    break  # trace exhausted and everything answered
            if not self._pending():
                # Idle: sleep to the next arrival (or the window edge)
                # instead of spinning the host loop dry.
                wake = arrivals[i].t if pending_arrivals else end_t
                delay = min(wake - now, 0.05)
                if delay > 0:
                    time.sleep(delay)
                # An idle stretch still advances SLO time (a breach
                # does not end because traffic paused).
                if self.slo is not None:
                    self.slo.evaluate(tick=self.tick)
                if on_tick is not None:
                    on_tick(self, now)
                continue
            self._run_tick()
            if on_tick is not None:
                on_tick(self, time.perf_counter() - t0)
        if self._pending():
            self._truncated = True
        if self.slo is not None:
            # One closing evaluation: work admitted/shed after the last
            # in-loop evaluate (e.g. the final burst before a
            # drain=False window edge) must still get a verdict.
            self.slo.evaluate(tick=self.tick)
            self.slo.finish()
        return self.completed

    # -- reporting ----------------------------------------------------------
    def _tenant_rollup(self) -> dict:
        """Per-tenant serving facts (ISSUE 12 satellite): completions,
        sheds, and the whole-run p95 TTFT from the stream registry's
        per-tenant sketch — the measurable surface the fairness
        invariant is checked against (tenants were previously only span
        labels). Empty when no request carried a tenant."""
        out: dict[str, dict] = {}
        for c in self.completed:
            if not c.tenant:
                continue
            e = out.setdefault(c.tenant, {"completed": 0, "shed": 0})
            e["completed"] += 1
        for r in self.shed:
            if not r.tenant:
                continue
            e = out.setdefault(r.tenant, {"completed": 0, "shed": 0})
            e["shed"] += 1
        if self.stream is not None:
            for t, e in out.items():
                sk = self.stream.total_sketch(f"request_ttft_tenant:{t}")
                if sk is not None and sk.count:
                    e["ttft_p95_s"] = round(sk.quantile(0.95), 6)
        return dict(sorted(out.items()))

    def _eviction_candidates(self, cap: int = 16) -> list:
        """Ranked list of what an eviction policy SHOULD reclaim first
        (ISSUE 18 tentpole c — the ROADMAP inventory item consumes
        this, ordered coldest-first by last-touch tick):

        - ``parked_victim``: a preempted request sitting in a policy
          queue. Its pages are already free — the bytes figure is the
          claim its re-admission will make (what NOT resuming it
          saves), stamped with the tick the preemption parked it.
        - ``idle_tail``: a live slot's exclusively-owned bytes. Live
          slots touch their cache every decode tick, so these rank
          hottest (last) — correct: evicting a decoding request is the
          most disruptive choice, listed only as the final resort.
        - ``sole_reader_prefix``: a prefix-index entry whose pages are
          all refcount 1 — nobody shares it anymore; retiring its one
          mapper returns the whole run. Nested page-aligned entries of
          the same registration are deduped to the longest.
        - ``host_prefix`` (ISSUE 20): a prefix entry already spilled to
          the host tier. Its bytes are host RAM, not HBM — reclaiming
          it buys host capacity and forfeits a restream hit.

        Every candidate carries its current ``tier`` ("hbm", "host",
        or "none" for parked victims whose pages were spilled/freed).
        """
        pb = self.engine.page_bytes
        out = []
        if self.policy is not None and pb:
            alloc = self.engine.allocator
            parked = getattr(alloc, "_parked", {})
            for st in self.policy._tiers.values():
                for q in st.queues.values():
                    for live in q:
                        if live.feed is None:
                            continue  # fresh submit, holds nothing yet
                        pages = alloc.pages_for(
                            len(live.feed), live.remaining_new()
                        )
                        out.append({
                            "kind": "parked_victim",
                            "rid": live.req.rid,
                            "tenant": live.req.tenant or "",
                            "bytes": int(pages * pb),
                            "last_touch_tick": live.park_tick,
                            "tier": "host" if live.req.rid in parked
                            else "none",
                        })
        if self._paged and pb:
            alloc = self.engine.allocator
            for slot, live in self.live.items():
                owned, _ = alloc.slot_page_stats(slot)
                out.append({
                    "kind": "idle_tail",
                    "rid": live.req.rid,
                    "tenant": live.req.tenant or "",
                    "bytes": int(owned * pb),
                    "last_touch_tick": live.last_touch,
                    "tier": "hbm",
                })
            best: dict[int, tuple] = {}
            for key, entry in alloc._index.items():
                if not entry.pages:
                    continue
                if entry.tier != "hbm":
                    # Host-resident entry: its page ids index the HOST
                    # namespace — running them through the device
                    # refcount would read the wrong pages. Reported
                    # below as its own candidate kind.
                    continue
                if any(int(alloc.refcount[p]) != 1 for p in entry.pages):
                    continue
                first = entry.pages[0]
                if first not in best or key[0] > best[first][0][0]:
                    best[first] = (key, entry)
            for key, entry in best.values():
                out.append({
                    "kind": "sole_reader_prefix",
                    "key": f"prefix[{key[0]}t]",
                    "bytes": int(len(entry.pages) * pb),
                    "last_touch_tick": alloc._prefix_touch.get(key, 0),
                    "tier": "hbm",
                })
            hbest: dict[int, tuple] = {}
            for key, entry in alloc._index.items():
                if entry.tier != "host" or not entry.pages:
                    continue
                first = entry.pages[0]
                if first not in hbest or key[0] > hbest[first][0][0]:
                    hbest[first] = (key, entry)
            for key, entry in hbest.values():
                out.append({
                    "kind": "host_prefix",
                    "key": f"prefix[{key[0]}t]",
                    "bytes": int(len(entry.pages) * pb),
                    "last_touch_tick": alloc._prefix_touch.get(key, 0),
                    "tier": "host",
                })
        elif not self._paged and self.engine.slot_bytes:
            for live in self.live.values():
                out.append({
                    "kind": "idle_tail",
                    "rid": live.req.rid,
                    "tenant": live.req.tenant or "",
                    "bytes": int(self.engine.slot_bytes),
                    "last_touch_tick": live.last_touch,
                    "tier": "hbm",
                })
        out.sort(key=lambda c: (c["last_touch_tick"],
                                str(c.get("rid", c.get("key", "")))))
        return out[:cap]

    def _memory_stats(self) -> dict:
        """The ``stats()["memory"]`` block (ISSUE 18): byte-exact held
        decomposition + conservation verdict from the ledger, live KV
        headroom, per-request/per-tenant attribution computed from
        allocator ground truth, the eviction-candidate ranking, and the
        device reconciliation (modeled-only off TPU — the roofline
        honesty rule). ``source: memledger`` is the marker the
        ``obs capacity`` CLI keys on."""
        ml = self._memledger
        if ml is None:
            return {}
        out = {
            "source": "memledger",
            "worker_id": self.worker_id,
            "role": self.role,
            "platform": ml.platform,
            "held_bytes": int(ml.held()),
            "held_peak_bytes": int(max(self._held_peak, int(ml.held()))),
            "held_by_subsystem": ml.decompose(),
            "conservation": ml.conservation(),
        }
        sub = "kv_pages" if self._paged else "kv_slots"
        cap = ml.capacity(sub)
        if cap:
            out["kv_capacity_bytes"] = int(cap)
            out.update(self._kv_headroom())
            out.pop("hbm_held_bytes", None)  # duplicate of held_bytes
        if self._headroom_min_pct is not None:
            out["kv_headroom_min_pct"] = self._headroom_min_pct
        if self._host_tier:
            # Host-tier ledger view (ISSUE 20). ``restream_bytes`` is
            # the key name the obs diff gate reports on — keep it.
            eng = self.engine
            held = int(ml.held("kv_host_pages"))
            self._host_held_peak = max(self._host_held_peak, held)
            out["host_held_bytes"] = held
            out["host_held_peak_bytes"] = int(self._host_held_peak)
            out["host_capacity_bytes"] = int(
                ml.capacity("kv_host_pages") or 0
            )
            out["spill_bytes_total"] = int(eng.host_spill_bytes)
            out["restream_bytes"] = int(eng.host_restream_bytes)
        per_req: dict[str, dict] = {}
        per_tenant: dict[str, int] = {}
        if self._paged and self.engine.page_bytes:
            alloc = self.engine.allocator
            pb = self.engine.page_bytes
            for slot, live in list(self.live.items()) + list(
                self.prefilling.items()
            ):
                owned, shared = alloc.slot_page_stats(slot)
                per_req[str(live.req.rid)] = {
                    "bytes": int(owned * pb),
                    "shared_pages": shared,
                    "tenant": live.req.tenant or "",
                }
            shared_pages = int((alloc.refcount >= 2).sum())
            out["shared_bytes"] = int(shared_pages * pb)
        elif not self._paged and self.engine.slot_bytes:
            for live in self.live.values():
                per_req[str(live.req.rid)] = {
                    "bytes": int(self.engine.slot_bytes),
                    "shared_pages": 0,
                    "tenant": live.req.tenant or "",
                }
        for e in per_req.values():
            t = e["tenant"]
            per_tenant[t] = per_tenant.get(t, 0) + e["bytes"]
        if per_req:
            out["per_request"] = dict(
                sorted(per_req.items(), key=lambda kv: -kv[1]["bytes"])
            )
            out["per_tenant"] = dict(
                sorted(per_tenant.items(), key=lambda kv: -kv[1])
            )
        ev = self._eviction_candidates()
        if ev:
            out["eviction_candidates"] = ev
        device = None
        if getattr(self.engine, "platform", None) == "tpu":
            import jax

            device = jax.devices()[0]
        out["reconciliation"] = ml.reconcile(device)
        snap = ml.snapshot()
        if "exhaustion" in snap:
            out["exhaustion"] = snap["exhaustion"]
            out["exhaustions"] = snap["exhaustions"]
        return out

    def stats(self) -> dict:
        """Host-side serving roll-up (the obs summary carries the
        span-derived histograms; this is the request-math view)."""
        done = self.completed
        out = {
            "worker_id": self.worker_id,
            "role": self.role,
            "requests_completed": len(done),
            "ticks": self.tick,
            "admissions": self.admissions,
            "generated_tokens": sum(len(c.tokens) for c in done),
            "occupancy_mean": round(
                self._occupancy_sum / max(self.tick, 1), 4
            ),
            # A run that stopped at max_ticks / the timed window with
            # work still queued or live is PARTIAL — indistinguishable
            # from finished without this flag (ISSUE 6 satellite).
            "truncated": self._truncated,
            # Most requests simultaneously resident (live + prefilling)
            # — the capacity number the paged-vs-dense bench pins.
            "concurrency_peak": self._concurrency_peak,
        }
        # The cache's wire dtype (ISSUE 15): what a cached row occupies
        # HBM as — "int8" on the quantized engines, the model dtype
        # otherwise. Always reported: capacity and bandwidth figures
        # are uninterpretable without it.
        kv_dtype = getattr(self.engine, "kv_dtype", None)
        if kv_dtype is not None:
            out["kv_dtype"] = kv_dtype
        # The weight store's wire dtype (ISSUE 17), same rule: "int8"
        # when the matmul weights live as int8+scales, "f32" otherwise.
        weights_dtype = getattr(self.engine, "weights_dtype", None)
        if weights_dtype is not None:
            out["weights_dtype"] = weights_dtype
        watch = getattr(self.engine, "compile_watch", None)
        if watch is not None:
            # The runtime-guarded compile claim (ISSUE 8): 2 for the
            # dense engine's lifetime (3 paged, + copy_page) — anything
            # above is an unexpected recompile the watch also flagged.
            out["engine_compiles"] = watch.compiles
        if self._decode_hbm_bytes:
            out["decode_hbm_bytes_modeled"] = round(
                self._decode_hbm_bytes, 1
            )
        if self._spec:
            # The speculative roll-up (ISSUE 13): tokens emitted per
            # slot-tick (1.0 = plain decode — the throughput
            # multiplier) and the drafted-token acceptance fraction.
            out["spec_k"] = self._spec
            out["spec_drafted_tokens"] = self._spec_drafted
            out["spec_accepted_tokens"] = self._spec_accepted
            if self._spec_active_ticks:
                out["accepted_tokens_per_tick"] = round(
                    self._spec_emitted / self._spec_active_ticks, 4
                )
                out["draft_acceptance_rate"] = round(
                    self._spec_accepted / max(self._spec_drafted, 1), 4
                )
        if self._paged:
            alloc = self.engine.allocator
            out.update(
                kv_page_size=alloc.page_size,
                kv_pool_pages=alloc.num_pages,
                kv_pool_occupancy_mean=round(
                    self._kv_occ_sum / max(self.tick, 1), 4
                ),
                kv_pool_occupancy_peak=round(self._kv_occ_peak, 4),
                prefix_hit_rate=round(alloc.hit_rate, 4),
                prefix_hits=alloc.prefix_hits,
                prefix_pages_shared_peak=self._pages_shared_peak,
                kv_cow_copies=alloc.cow_copies,
            )
            if self._host_tier:
                # Host-tier roll-up (ISSUE 20): tier occupancy plus the
                # spill/restream traffic and where prefix hits landed.
                eng = self.engine
                out.update(
                    kv_host_pages=alloc.host_pages,
                    kv_host_pages_in_use=alloc.host_pages_in_use,
                    host_spilled_pages=eng.host_spilled_pages,
                    host_restreamed_pages=eng.host_restreamed_pages,
                    host_prefix_hits=alloc.host_prefix_hits,
                    parked_spills=alloc.parked_spills,
                    spilled_prefix_entries=alloc.spilled_prefix_entries,
                    promoted_entries=alloc.promoted_entries,
                )
            # Resume-path p95s (ISSUE 20 headline): recorded for every
            # paged server — an untiered run yields the recompute p95
            # the bench compares the restream p95 against.
            for mode, durs in sorted(self.resume_durations.items()):
                if durs:
                    out[f"resume_{mode}_p95_s"] = round(
                        float(np.percentile(np.asarray(durs), 95)), 6
                    )
        if self.shed:
            # Cause breakdown (ISSUE 16 satellite): ``requests_shed``
            # is a dict — total plus the two named reasons (bounded
            # intake vs the projected-TTFT admission verdict), zeros
            # included so a reader never KeyErrors on the quiet cause.
            # The flat ``requests_shed_<cause>`` keys stay for the
            # bench record line and older readers.
            out["requests_shed"] = {
                "total": len(self.shed),
                "shed_queue_full": self.shed_causes.get("queue_full", 0),
                "shed_admission_projection": self.shed_causes.get(
                    "admission", 0
                ),
            }
            for cause, n in sorted(self.shed_causes.items()):
                out[f"requests_shed_{cause}"] = n
        if self.policy is not None:
            pol = self.policy.stats()
            out["preemptions"] = pol["preemptions"]
            out["policy"] = pol
        if self._ledger is not None:
            # Why-slow surfacing (ISSUE 16): the retained tail
            # exemplars, worst first, plus the ledger's aggregate view.
            out["exemplars"] = self._ledger.exemplars()
            out["ledger"] = self._ledger.stats()
        tenants = self._tenant_rollup()
        if tenants:
            out["tenants"] = tenants
        memory = self._memory_stats()
        if memory:
            out["memory"] = memory
        if done:
            lat = np.asarray([c.latency_s for c in done])
            ttft = np.asarray([c.ttft_s for c in done])
            out.update(
                latency_p50_s=round(float(np.percentile(lat, 50)), 6),
                latency_p95_s=round(float(np.percentile(lat, 95)), 6),
                ttft_p50_s=round(float(np.percentile(ttft, 50)), 6),
                ttft_p95_s=round(float(np.percentile(ttft, 95)), 6),
            )
        return out
