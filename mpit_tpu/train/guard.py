"""Failure detection + recovery (SURVEY.md §6 "Failure detection" row).

The reference has none: a diverged or dead worker hangs/aborts the whole
``mpirun`` job. TPU-natively the failure modes that remain after the SPMD
collapse are *numeric* — a NaN/Inf loss or a blow-up — and the recovery
story is checkpoint-restart (SURVEY.md §6): detect at the metric fetch
(which the loop already pays for), restore the last good sharded
checkpoint, and continue.

Detection is deliberately cheap: checks ride the existing log-point host
fetch; no extra device syncs are inserted into the hot loop.

Lagged detection (ISSUE 2): with the async metric pipeline the loop no
longer blocks on ``float(loss)`` at the fence where a step ran — it
starts a host copy and consumes the value up to ``lag`` fences later.
The guard therefore accepts a *lag window*: ``check`` takes the step the
loss belongs to plus the (later) step the loop had reached when the
value arrived, and the raised :class:`Diverged` carries both. The
restore POLICY is unchanged — a failing loss still restores the newest
checkpoint older than the previous restore target — only the detection
point moves, by at most ``lag`` fence intervals.
"""

from __future__ import annotations

import math


class Diverged(RuntimeError):
    """Training produced a non-finite or exploding loss.

    ``step`` is the step whose loss failed; ``detected_step`` is where
    the loop's host side had advanced to when the (possibly async)
    fetch delivered the value — equal to ``step`` for synchronous
    detection, up to ``lag`` fences later for the pipelined path.
    """

    def __init__(
        self, step: int, loss: float, reason: str,
        detected_step: int | None = None,
    ):
        detected_step = step if detected_step is None else detected_step
        late = (
            f", detected at step {detected_step}"
            if detected_step != step else ""
        )
        super().__init__(
            f"training diverged at step {step}: loss={loss} ({reason}{late})"
        )
        self.step = step
        self.loss = loss
        self.reason = reason
        self.detected_step = detected_step


class DivergenceGuard:
    """Loss sanity checks at log points.

    - non-finite loss: always fatal (raises :class:`Diverged`);
    - spike detection (opt-in via ``spike_factor > 0``): raises when the
      loss exceeds ``spike_factor ×`` its EMA, after ``warmup`` healthy
      checks (early-training noise is not a spike);
    - lag window (``lag ≥ 0``, ISSUE 2): the loop may deliver the loss
      of step N while its host side is already at step N + lag·fence.
      ``check`` accepts the delivery point as ``detected_step`` and
      enforces that the delay never exceeds the declared window — a
      pipeline that silently grows its backlog would otherwise turn
      "detection delayed ≤ k" into "detection delayed unboundedly".
    """

    def __init__(
        self, *, spike_factor: float = 0.0, ema: float = 0.9,
        warmup: int = 5, lag: int = 0, fence: int = 1,
    ):
        self.spike_factor = spike_factor
        self.lag = lag
        self.fence = max(1, fence)
        self._ema_coef = ema
        self._warmup = warmup
        self._ema: float | None = None
        self._window: list[float] = []

    def check(
        self, step: int, loss: float, *, detected_step: int | None = None
    ) -> None:
        detected = step if detected_step is None else detected_step
        if detected - step > self.lag * self.fence:
            raise RuntimeError(
                f"DivergenceGuard: loss for step {step} delivered at step "
                f"{detected}, past the declared lag window "
                f"({self.lag} fences x {self.fence} steps) — the async "
                "metric pipeline is not bounding its backlog"
            )
        if not math.isfinite(loss):
            raise Diverged(step, loss, "non-finite", detected_step=detected)
        if len(self._window) < self._warmup:
            # Warmup: tolerate transients AND keep them out of the
            # baseline — the EMA seeds from the warmup *median*, so one
            # huge early outlier cannot inflate it and mask later spikes.
            self._window.append(loss)
            if len(self._window) == self._warmup:
                self._ema = sorted(self._window)[self._warmup // 2]
            return
        assert self._ema is not None
        if self.spike_factor > 0 and loss > self.spike_factor * self._ema:
            raise Diverged(
                step, loss,
                f"spike > {self.spike_factor}x EMA {self._ema:.4g}",
                detected_step=detected,
            )
        self._ema = self._ema_coef * self._ema + (1 - self._ema_coef) * loss

    def reset(self) -> None:
        """Forget history (call after a checkpoint restore)."""
        self._ema = None
        self._window = []
