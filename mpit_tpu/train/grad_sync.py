"""GradSync — bucketed gradient synchronization over the ring tier.

ISSUE 9 tentpole (c): the one hot path XLA still owned was the wire —
training kernels are hand-built Pallas but gradient sync was stock
``lax.psum``/``psum_scatter``. :class:`GradSync` makes the sync strategy
a selectable policy of the training step
(``grad_sync="psum"(default) | "ring" | "ring_q8"``):

- ``psum``     — the stock XLA collectives, byte-for-byte the seed
  behavior (this mode exists so the other two have a pinned oracle).
- ``ring``     — the in-kernel Pallas ring (``ops/ring_collectives``),
  issued PER BUCKET: the flat gradient is split into fixed-size buckets
  and each bucket's reduce-scatter is an independent collective, so
  XLA's latency-hiding scheduler can start syncing late-layer gradients
  while the tail of backward still computes early-layer ones (the
  bucket-granularity overlap of the classic DDP design — within one
  jitted step, overlap is the scheduler's to exploit; the buckets give
  it the freedom a single monolithic collective denies). Numerically
  identical to ``psum`` (elementwise sums; pinned).
- ``ring_q8``  — the ring with the EQuARX-spirit int8 wire (per-chunk
  scales, dequant-accumulate in f32): ~¼ the wire bytes, lossy by
  design — convergence neutrality is the contract (MNIST/AlexNet
  loss-curve pin vs f32 sync), bit-match is NOT claimed.

LAYOUT INVARIANT (the reason checkpoints stay interchangeable between
modes): every mode produces the SAME contiguous per-device shard —
``opt.sharded.shard_of``'s ``[i·S, (i+1)·S)`` of the ``n·LANE``-padded
flat vector. Buckets are row-ranges OF THE SHARD (boundaries at 32-row
multiples, the int8 tile, so every bucket is wire-aligned for any
dtype; the tail bucket's remainder is tile-padded per chunk inside the
shared ring planner). A bucketed reduce-scatter therefore scatters
bucket ``b`` of every device's chunk to the owner of that chunk, and
the concatenation over buckets IS the contiguous shard — no permuted
layouts, no optimizer-state migration between sync modes.

Buckets are chained with ``lax.optimization_barrier`` tokens: ring
kernels share one ``collective_id`` (barrier semaphore), so two rings
must never be scheduled concurrently (``ops/ring_collectives``
docstring) — and serializing the collectives among themselves is also
what a real wire wants (they contend for the same ICI links; the
overlap win is collectives-under-compute, which the token chain does
not constrain).

Composition (ISSUE 9: "composing with the existing stx sharded-update
path rather than duplicating it"): ``opt.sharded.sharded(tx, axis,
comm=gs)`` delegates its three choreography points (grad
reduce-scatter, param shard select, update all-gather) to this object;
``make_train_step(grad_sync=...)`` builds it and threads it through
both the ZeRO-1 and the plain-DP path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from mpit_tpu.comm import collectives as C

# ONE pad rule (n·LANE) across the ring stack: the checkpoint-
# interchangeability contract depends on every mode agreeing on it, so
# the helper is imported from the layout authority, not re-spelled.
from mpit_tpu.opt.sharded import _pad_to, flat_ravel, shard_of

_LANE = 128
# Bucket boundaries are multiples of the int8 tile (32 rows) so every
# non-tail bucket is wire-aligned for f32, bf16 AND int8 payloads.
_BUCKET_ALIGN_ROWS = 32

GRAD_SYNC_MODES = ("psum", "ring", "ring_q8")


class GradSync:
    """Bucketed gradient-sync policy (see module docstring).

    Built once per training step (cheap, stateless); every method is
    traceable and must be called *inside* ``shard_map`` over ``axis``.

    Args:
      axis: mesh axis the gradients sync over.
      mode: ``"psum" | "ring" | "ring_q8"``.
      bucket_mb: target bucket size in MB of f32 elements (the flat
        vector is split into ``ceil(size / bucket)`` ring collectives;
        one bucket ≡ the monolithic collective). Ignored for ``psum``.
      interpret: run the ring kernels in TPU interpret mode (CPU tests);
        ``None``/``False`` = compiled path, which falls back to the
        exact ``lax`` composition off-TPU (mode-stamped in obs).
    """

    def __init__(
        self,
        axis: str,
        mode: str = "psum",
        *,
        bucket_mb: float = 4.0,
        interpret: bool | None = None,
    ):
        if mode not in GRAD_SYNC_MODES:
            raise ValueError(
                f"grad_sync must be one of {GRAD_SYNC_MODES}, got {mode!r}"
            )
        if bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be positive, got {bucket_mb}")
        self.axis = axis
        self.mode = mode
        self.bucket_mb = float(bucket_mb)
        self.interpret = bool(interpret)

    # ----- host-side labels / models --------------------------------------

    @property
    def quantized(self) -> bool:
        return self.mode == "ring_q8"

    @property
    def exec_mode(self) -> str:
        """What actually executes ON THIS HOST — the span label the
        training loop stamps (the way serve stamps ``attention=``), so
        a fallback run can never be misattributed (ISSUE 9 satellite).
        """
        if self.mode == "psum":
            return "psum"
        on_ring = self.interpret or jax.devices()[0].platform == "tpu"
        if self.mode == "ring":
            return "ring" if on_ring else "psum_fallback"
        return "ring_q8" if on_ring else "ring_q8_emulated"

    def wire_scale(self, dtype=jnp.float32) -> float:
        """Bytes-on-wire per logical payload byte — the factor the
        modeled comm accounting (``utils.CommModel(wire_scale=...)``,
        roofline ICI attribution, P2P matrix) must apply so quantized
        sync is modeled at its ACTUAL size (int8: ¼ of f32, ½ of
        bf16), not the
        logical one. Scale-block overhead is payload-dependent and
        small (one 4 KB block per chunk); it is charged exactly by the
        trace-time ``_rec`` accounting and ignored here."""
        if not self.quantized:
            return 1.0
        return 1.0 / jnp.dtype(dtype).itemsize

    # ----- bucket planner --------------------------------------------------

    def bucket_rows(self, shard_rows: int) -> list[tuple[int, int]]:
        """Row ranges ``[(r0, r1), ...]`` of the per-device
        ``[shard_rows, LANE]`` shard view, one ring collective each.
        Boundaries are multiples of 32 rows; the tail keeps the
        remainder (its per-chunk tile pad is the ring planner's job)."""
        per = int(self.bucket_mb * 2**20) // (4 * _LANE)  # f32 rows
        per = max(_BUCKET_ALIGN_ROWS, per - per % _BUCKET_ALIGN_ROWS)
        out = []
        r = 0
        while r < shard_rows:
            out.append((r, min(r + per, shard_rows)))
            r += per
        return out

    # ----- the three choreography points (called by opt.sharded) ----------

    def scatter_grads(self, flat):
        """Sum-reduce-scatter the flat local gradient: returns this
        device's contiguous shard of the cross-device sum (the ZeRO-1
        reduce-scatter, ``opt.sharded`` divides by N for the mean)."""
        n = lax.axis_size(self.axis)
        if self.mode == "psum":
            # Byte-for-byte the seed choreography ([rows, LANE] view —
            # see opt.sharded's tile-friendly-layout rules).
            g2 = _pad_to(flat, n * _LANE).reshape(-1, _LANE)
            return C.reduce_scatter(g2, self.axis).reshape(-1)
        padded = _pad_to(flat, n * _LANE)
        rows_s = padded.shape[0] // (n * _LANE)
        x3 = padded.reshape(n, rows_s, _LANE)
        op = "qsum" if self.quantized else "sum"
        from mpit_tpu.ops.ring_collectives import ring_reduce_scatter

        shards, token = [], None
        for r0, r1 in self.bucket_rows(rows_s):
            xb = x3[:, r0:r1, :].reshape(-1, _LANE)
            if token is not None:
                # Serialize rings (shared collective_id; see module
                # docstring) without constraining the backward compute
                # they overlap with.
                xb, token = lax.optimization_barrier((xb, token))
            sb = ring_reduce_scatter(
                xb, self.axis, op=op, interpret=self.interpret
            )
            token = sb
            shards.append(sb.astype(flat.dtype))
        return jnp.concatenate(shards) if len(shards) > 1 else shards[0]

    def param_shard(self, flat):
        """This device's contiguous shard of the flat params — the SAME
        layout every mode scatters into (``opt.sharded.shard_of``)."""
        return shard_of(flat, self.axis)

    def gather_updates(self, u_shard, size: int):
        """All-gather the per-shard updates back to the full flat
        vector (replicated-typed, ``[:size]``) — the ZeRO-1 gather."""
        n = lax.axis_size(self.axis)
        if self.mode == "psum":
            return C.allgather(
                u_shard.reshape(-1, _LANE), self.axis, tiled=True,
                invariant=True,
            ).reshape(-1)[:size]
        rows_s = u_shard.shape[0] // _LANE
        u2 = u_shard.reshape(rows_s, _LANE)
        from mpit_tpu.ops.ring_collectives import ring_all_gather

        pieces, token = [], None
        for r0, r1 in self.bucket_rows(rows_s):
            xb = u2[r0:r1, :]
            if token is not None:
                xb, token = lax.optimization_barrier((xb, token))
            gb = ring_all_gather(
                xb, self.axis, quantized=self.quantized,
                interpret=self.interpret,
            )
            token = gb
            pieces.append(
                gb.reshape(n, (r1 - r0) * _LANE).astype(u_shard.dtype)
            )
        full = (
            jnp.concatenate(pieces, axis=1) if len(pieces) > 1 else pieces[0]
        )
        return full.reshape(-1)[:size]

    # ----- plain-DP (zero1=False) path ------------------------------------

    def allreduce_grads(self, grads):
        """Mean-allreduce a gradient pytree — the plain-DP sync
        (``lax.pmean`` in psum mode, bucketed ring RS+AG otherwise;
        the ring forms flatten via the lane-aligned ``flat_ravel`` so
        bucket boundaries never split a tile)."""
        if self.mode == "psum":
            return jax.tree.map(lambda g: lax.pmean(g, self.axis), grads)
        n = lax.axis_size(self.axis)
        flat, unravel = flat_ravel(grads)
        shard = self.scatter_grads(flat) / n
        full = self.gather_updates(shard, flat.shape[0])
        return unravel(full)
