"""Continuous batching: the request loop over the slot-batched engine.

The reference's pserver is a tag-dispatched request-serving loop
(SURVEY.md §3.2 A1) — receive, act, reply, forever. This is that
capability rebuilt for inference: requests queue on the host, are
admitted into freed KV-cache slots BETWEEN decode ticks (no tick waits
for a full batch — a new request rides the next prefill while everyone
else keeps decoding), and retire per-slot on EOS / max-new-tokens /
cache-full, freeing the slot for the next queue entry immediately.

Observability (``mpit_tpu.obs``) is first-class, not bolted on:

- spans: ``prefill`` (per admission batch) and ``decode`` (per tick) —
  both close on the host fetch of the sampled tokens, so their wall
  clock covers real device completion;
- per-request intervals recorded with explicit timestamps
  (``obs.span_at``): ``queue_wait`` (submit → admit), ``request_ttft``
  (submit → first token) and ``request_latency`` (submit → retire) —
  the summary's per-phase p50/p95 roll-up then IS the latency/TTFT
  histogram, and the Chrome trace shows every request as a bar;
- ``slot_occupancy`` gauge + ``serve_tokens``/``serve_requests``
  counters each tick.

An optional :class:`mpit_tpu.obs.Sentinel` (``phases=("decode",
"prefill")``) watches the tick stream for spikes/sustained degradation
— the serving analogue of the training loop's step-wall sentinel.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any

import numpy as np

from mpit_tpu import obs
from mpit_tpu.ops.decode_attention import num_kv_blocks

__all__ = ["Request", "Completed", "Server"]


@dataclasses.dataclass
class Request:
    """One generation request. ``temperature <= 0`` = greedy;
    ``top_k = 0`` = full vocab; ``eos_id = None`` = never stop early."""

    rid: Any
    prompt: list[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    eos_id: int | None = None


@dataclasses.dataclass
class Completed:
    """A finished request: output + the latency facts the histograms
    aggregate. ``tokens`` includes the EOS token when one stopped it."""

    rid: Any
    prompt: list[int]
    tokens: list[int]
    submit_t: float
    first_token_t: float
    finish_t: float
    truncated: bool = False  # retired by cache-full, not EOS/max-tokens

    @property
    def ttft_s(self) -> float:
        return self.first_token_t - self.submit_t

    @property
    def latency_s(self) -> float:
        return self.finish_t - self.submit_t


@dataclasses.dataclass
class _Live:
    req: Request
    submit_t: float
    first_token_t: float = 0.0
    tokens: list = dataclasses.field(default_factory=list)


class Server:
    """The continuous-batching loop around one :class:`~mpit_tpu.serve.Engine`.

    Host-side only: slot bookkeeping, the request queue, retirement and
    telemetry. ``submit()`` enqueues; ``run()`` drives admit/decode
    ticks until the queue and all slots drain (or ``max_ticks``).
    """

    def __init__(self, engine, *, sentinel=None):
        self.engine = engine
        self.sentinel = sentinel
        # The attention mode + sampler actually executing — stamped on
        # every prefill/decode span so the flight recorder / sentinel can
        # attribute a serve-path regression to a kernel fallback (ISSUE 5
        # obs satellite). Both labels matter: off-TPU "kernel" mode runs
        # reference ATTENTION but keeps the blocked SAMPLER, so
        # attention=reference alone does not identify the PR 4 path.
        self._attn_mode = getattr(
            engine, "decode_attention_mode", "reference"
        )
        self._sampler = getattr(engine, "decode_sampler", "dense")
        self.queue: deque[_Live] = deque()
        self.live: dict[int, _Live] = {}  # slot -> in-flight request
        self.free: list[int] = list(range(engine.slots))[::-1]  # pop() = slot 0 first
        self.completed: list[Completed] = []
        self.tick = 0
        self.admissions = 0
        self._occupancy_sum = 0.0
        # Per-slot sampling-control arrays (host; refreshed on admit/retire).
        s = engine.slots
        self._temp = np.zeros((s,), np.float32)
        self._topk = np.zeros((s,), np.int32)

    # -- intake -------------------------------------------------------------
    def submit(self, req: Request) -> None:
        if not req.prompt:
            raise ValueError(f"request {req.rid!r}: empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.rid!r}: max_new_tokens must be >= 1 "
                f"(prefill always samples the first token), got "
                f"{req.max_new_tokens}"
            )
        if len(req.prompt) > self.engine.prefill_len:
            raise ValueError(
                f"request {req.rid!r}: prompt length {len(req.prompt)} > "
                f"engine prefill_len {self.engine.prefill_len}"
            )
        if len(req.prompt) + req.max_new_tokens > self.engine.max_len:
            raise ValueError(
                f"request {req.rid!r}: prompt + max_new_tokens "
                f"({len(req.prompt)} + {req.max_new_tokens}) exceeds the "
                f"engine's max_len {self.engine.max_len}"
            )
        k_cap = getattr(self.engine, "sample_k_cap", None)
        if k_cap is not None and req.top_k > k_cap:
            raise ValueError(
                f"request {req.rid!r}: top_k {req.top_k} exceeds the "
                f"blocked sampler's candidate buffer (sample_k_cap="
                f"{k_cap}); raise Engine(sample_k_cap=...) or use "
                f"top_k=0 (full vocab)"
            )
        self.queue.append(_Live(req, time.perf_counter()))

    # -- the loop -----------------------------------------------------------
    def _admit(self) -> None:
        """Move queued requests into free slots and prefill them (one
        batched call however many were admitted this tick)."""
        if not self.queue or not self.free:
            return
        s, plen = self.engine.slots, self.engine.prefill_len
        tokens = np.zeros((s, plen), np.int32)
        lens = np.ones((s,), np.int32)
        admit = np.zeros((s,), bool)
        batch: list[tuple[int, _Live]] = []
        now = time.perf_counter()
        while self.queue and self.free:
            live = self.queue.popleft()
            slot = self.free.pop()
            p = live.req.prompt
            tokens[slot, : len(p)] = p
            lens[slot] = len(p)
            admit[slot] = True
            self._temp[slot] = live.req.temperature
            self._topk[slot] = live.req.top_k
            obs.span_at("queue_wait", live.submit_t, now, rid=live.req.rid)
            batch.append((slot, live))
        with obs.span(
            "prefill", admitted=len(batch), attention=self._attn_mode,
            sampler=self._sampler,
        ):
            first = self.engine.prefill(
                tokens, lens, admit, self._temp, self._topk
            )
        t_first = time.perf_counter()
        self.admissions += len(batch)
        if self.sentinel is not None:
            self.sentinel.observe_phases(
                self.tick, prefill=t_first - now
            )
        for slot, live in batch:
            live.first_token_t = t_first
            live.tokens = [int(first[slot])]
            obs.span_at(
                "request_ttft", live.submit_t, t_first, rid=live.req.rid
            )
            self.live[slot] = live
            self._maybe_retire(slot, t_first)

    def _maybe_retire(self, slot: int, now: float) -> None:
        """Retire ``slot`` if its newest token finished the request."""
        live = self.live[slot]
        req = live.req
        tok = live.tokens[-1]
        # Host mirror of the device cache fill: prefill cached the prompt,
        # each decode tick appends ONE token (the newest sampled token is
        # not yet written). The next decode would write at this position —
        # at max_len the slot must retire or it would overrun the buffer.
        cache_len = len(req.prompt) + len(live.tokens) - 1
        full = cache_len >= self.engine.max_len
        done = (
            (req.eos_id is not None and tok == req.eos_id)
            or len(live.tokens) >= req.max_new_tokens
            or full
        )
        if not done:
            return
        del self.live[slot]
        self.free.append(slot)
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        obs.span_at("request_latency", live.submit_t, now, rid=req.rid)
        obs.counter("serve_requests")
        self.completed.append(
            Completed(
                rid=req.rid,
                prompt=list(req.prompt),
                tokens=list(live.tokens),
                submit_t=live.submit_t,
                first_token_t=live.first_token_t,
                finish_t=now,
                truncated=full
                and tok != req.eos_id
                and len(live.tokens) < req.max_new_tokens,
            )
        )

    def _decode_tick(self) -> None:
        active = np.zeros((self.engine.slots,), bool)
        for slot in self.live:
            active[slot] = True
        t0 = time.perf_counter()
        with obs.span(
            "decode", active=int(active.sum()), attention=self._attn_mode,
            sampler=self._sampler,
        ):
            toks = self.engine.decode(active, self._temp, self._topk)
        now = time.perf_counter()
        if self.sentinel is not None:
            self.sentinel.observe_phases(self.tick, decode=now - t0)
        obs.counter("serve_tokens", float(active.sum()))
        if self._attn_mode == "kernel" and self.live:
            # Cache tiles the length-aware kernel skipped this tick —
            # ONE formula, num_kv_blocks, shared with the kernel's own
            # in-kernel bound (pinned against it in
            # tests/test_decode_attention.py), so the counter cannot
            # drift from what the kernel actually visits. A serve
            # regression with this counter flat at 0 = kernel fallback.
            # The decode step runs over ALL slots: free slots' lengths
            # are clamped to 0 in-step, so each one visits exactly 1
            # tile — counted here too, or the counter would understate
            # the skipping the clamp buys.
            bk = self.engine.decode_block_k
            total = self.engine.max_len // bk
            lens = np.asarray(
                [
                    len(live.req.prompt) + len(live.tokens) - 1
                    for live in self.live.values()
                ]
            )
            visited = num_kv_blocks(lens, 1, self.engine.max_len, bk)
            n_free = self.engine.slots - lens.size
            obs.counter(
                "decode_blocks_skipped",
                float(
                    total * self.engine.slots
                    - int(visited.sum())
                    - n_free  # 1 visited tile per clamped free slot
                ),
            )
        for slot in list(self.live):
            self.live[slot].tokens.append(int(toks[slot]))
            self._maybe_retire(slot, now)

    def run(self, *, max_ticks: int = 1_000_000) -> list[Completed]:
        """Drive admit/decode until everything submitted has completed
        (then return ALL completions so far, in finish order)."""
        while (self.queue or self.live) and self.tick < max_ticks:
            self._admit()
            occupancy = len(self.live) / self.engine.slots
            self._occupancy_sum += occupancy
            obs.gauge("slot_occupancy", occupancy)
            if self.live:
                self._decode_tick()
            self.tick += 1
        return self.completed

    # -- reporting ----------------------------------------------------------
    def stats(self) -> dict:
        """Host-side serving roll-up (the obs summary carries the
        span-derived histograms; this is the request-math view)."""
        done = self.completed
        out = {
            "requests_completed": len(done),
            "ticks": self.tick,
            "admissions": self.admissions,
            "generated_tokens": sum(len(c.tokens) for c in done),
            "occupancy_mean": round(
                self._occupancy_sum / max(self.tick, 1), 4
            ),
        }
        if done:
            lat = np.asarray([c.latency_s for c in done])
            ttft = np.asarray([c.ttft_s for c in done])
            out.update(
                latency_p50_s=round(float(np.percentile(lat, 50)), 6),
                latency_p95_s=round(float(np.percentile(lat, 95)), 6),
                ttft_p50_s=round(float(np.percentile(ttft, 50)), 6),
                ttft_p95_s=round(float(np.percentile(ttft, 95)), 6),
            )
        return out
