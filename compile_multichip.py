"""AOT-compile the framework's multi-chip programs for a REAL v5e-8 topology.

The CPU fake-mesh dryrun (``__graft_entry__.dryrun_multichip``) validates
semantics; this check validates what only the real TPU compiler can see —
Mosaic kernel lowering, layout-pass tile padding, per-chip memory. No TPU
pod is needed: ``jax.experimental.topologies`` supplies device proxies and
the installed TPU compiler does the rest (``mpit_tpu/utils/aot.py``).

Run: ``python compile_multichip.py [topology]`` (default ``v5e:2x4``).
Writes ``MULTICHIP_AOT.json`` with per-phase status + compiled-memory
numbers; exits non-zero if any phase fails to compile.

Phases (mirroring the dryrun, plus the memory-regression shape):

1.  ``dp-zero1``        — GPT-2 small DP step, goo state sharded (ZeRO-1).
2.  ``dp-zero1-moe322m``— the 322M-param GPT-2-MoE step with ZeRO-1 ON:
    the exact configuration whose 1-D flat scatter tile-padded 16x and
    compile-OOMed in round 3 (bench.py r3 docstring). Asserts temp memory
    stays under 4x the parameter payload.
3.  ``tp``              — GSPMD tensor-parallel GPT-2 step.
4.  ``pp-1f1b``         — pipeline parallel, 1F1B schedule, ZeRO-1.
5.  ``pp-interleaved-v2`` — interleaved 1F1B, V=2 virtual stages per
    device (round-5: closes the last un-AOT'd schedules).
6.  ``3d-dp-tp-pp``     — Megatron blocks as pipeline stages.
7.  ``3d-dp-cp-tp``     — ring attention inside the TP block (Pallas
    ring-flash kernel compiled by Mosaic for the topology).
8.  ``ulysses-in-tp``   — the Ulysses seq↔head all-to-all inside the
    Megatron block on the dp×cp×tp mesh (round-5).
9.  ``cp-long-context-16k`` — the CP training step at 16,384 global
    tokens over 8 ring shards (per-shard T=2048 under the flash
    kernel's auto head-grouping).
10. ``ep-moe``          — expert-parallel MoE, per-group ZeRO-1 (round 5:
    the sort/ragged dispatch — the one-hot path's [S,E,C] memory is gone).
11. ``hybrid-dcn``      — the slice-major hybrid-mesh DP step over two
    VIRTUAL slices (see phase docstring for the topology-API limitation).
12. ``pallas-ring-allreduce`` — the native-tier DMA kernel.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

# Persistent compile cache (same dir as bench.py): repeated AOT runs —
# and the driver's — replay cached compilations instead of paying the
# multi-minute phases again.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from mpit_tpu.utils.aot import (
    abstractify,
    aot_compile,
    memory_report,
    topology_world,
)


def _params_mb(params) -> float:
    return sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree.leaves(params)
    ) / 2**20


def _abstract_params(model, *init_args):
    out = jax.eval_shape(
        lambda: model.init(jax.random.key(0), *init_args)
    )
    return out["params"]


def phase_dp_zero1(topology):
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.train import make_train_step

    world = topology_world({"data": 8}, topology)
    seq, batch = 512, 48
    cfg = GPT2Config.small(max_seq_len=seq, head_dtype=jnp.bfloat16)
    model = GPT2(cfg)
    params = _abstract_params(model, jnp.zeros((1, seq), jnp.int32))

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["tokens"][:, :-1])
        return GPT2.loss_fn(logits, b["tokens"]), {}

    init_fn, step_fn, state_specs = make_train_step(
        loss_fn, goo_adam(3e-4), world, zero1=True
    )
    state = abstractify(
        jax.eval_shape(init_fn, params), world.mesh, state_specs(params)
    )
    batch_abs = abstractify(
        {"tokens": jax.ShapeDtypeStruct((batch, seq + 1), jnp.int32)},
        world.mesh,
        P("data"),
    )
    compiled = aot_compile(step_fn.build(params), state, batch_abs)
    return {"params_mb": round(_params_mb(params), 1), **memory_report(compiled)}


def phase_dp_zero1_moe322m(topology):
    """The round-3 compile-OOM configuration, ZeRO-1 ON."""
    from mpit_tpu.models import GPT2Config
    from mpit_tpu.models.gpt2_moe import GPT2MoE, MoESettings
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.train import make_train_step

    world = topology_world({"data": 8}, topology)
    seq, batch = 256, 64
    cfg = GPT2Config.small(max_seq_len=seq, head_dtype=jnp.bfloat16)
    model = GPT2MoE(cfg, MoESettings(num_experts=8, k=2, capacity_factor=1.25, every=2))
    params = _abstract_params(model, jnp.zeros((1, seq), jnp.int32))

    def loss_fn(p, b):
        losses, aux = model.apply(
            {"params": p}, b["tokens"][:, :-1], targets=b["tokens"][:, 1:]
        )
        return jnp.mean(losses) + 0.01 * aux, {}

    init_fn, step_fn, state_specs = make_train_step(
        loss_fn, goo_adam(3e-4), world, zero1=True, scan_steps=2
    )
    state = abstractify(
        jax.eval_shape(init_fn, params), world.mesh, state_specs(params)
    )
    batch_abs = abstractify(
        {"tokens": jax.ShapeDtypeStruct((2, batch, seq + 1), jnp.int32)},
        world.mesh,
        P(None, "data"),
    )
    compiled = aot_compile(step_fn.build(params), state, batch_abs)
    rep = memory_report(compiled)
    payload = _params_mb(params) * 2**20
    # The regression assertion: round 3's pathology was temp ~16x payload.
    assert rep["temp_bytes"] < 4.0 * payload, (
        f"ZeRO-1 temp memory {rep['temp_bytes']/2**30:.2f} GiB exceeds 4x "
        f"the {payload/2**30:.2f} GiB parameter payload — tile-pad "
        "pathology regressed (opt/sharded.py lane-aligned layout)"
    )
    return {"params_mb": round(payload / 2**20, 1), **rep}


def phase_tp(topology):
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.parallel import gpt2_tp_rules, make_pjit_train_step

    world = topology_world({"data": 4, "model": 2}, topology)
    seq = 512
    # Megatron-style vocab padding: the embedding shards over the model
    # axis, so the vocab must divide by it (50304 = 50257 padded to 128).
    cfg = GPT2Config.small(
        max_seq_len=seq, head_dtype=jnp.bfloat16, vocab_size=50304
    )
    model = GPT2(cfg)
    params = _abstract_params(model, jnp.zeros((1, seq), jnp.int32))

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["tokens"][:, :-1])
        return GPT2.loss_fn(logits, b["tokens"]), {}

    init_fn, step_fn, shardings_fn = make_pjit_train_step(
        loss_fn, goo_adam(3e-4), world, gpt2_tp_rules("model")
    )
    state_shapes = jax.eval_shape(init_fn, params)
    shardings = shardings_fn(params)
    state = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        state_shapes,
        shardings,
    )
    batch = {"tokens": jax.ShapeDtypeStruct((16, seq + 1), jnp.int32)}
    batch_abs = abstractify(batch, world.mesh, P("data"))
    compiled = aot_compile(step_fn.build(params, batch), state, batch_abs)
    return {"params_mb": round(_params_mb(params), 1), **memory_report(compiled)}


def phase_pp_1f1b(topology):
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.parallel import make_gpt2_pp_train_step, split_gpt2_params

    world = topology_world({"data": 2, "pipe": 4}, topology)
    seq = 256
    cfg = GPT2Config.small(max_seq_len=seq, head_dtype=jnp.bfloat16, tie_head=False)
    model = GPT2(cfg)
    full = _abstract_params(model, jnp.zeros((1, seq), jnp.int32))
    split = jax.eval_shape(
        lambda p: split_gpt2_params(p, cfg.num_layers, 4), full
    )
    init_fn, step_fn, state_specs = make_gpt2_pp_train_step(
        cfg, goo_adam(3e-4), world, num_microbatches=4, zero1=True,
        schedule="1f1b",
    )
    specs = state_specs(split)
    state = abstractify(jax.eval_shape(init_fn, split), world.mesh, specs)
    batch_abs = abstractify(
        {"tokens": jax.ShapeDtypeStruct((8, seq + 1), jnp.int32)},
        world.mesh,
        P("data"),
    )
    compiled = aot_compile(step_fn.build(split), state, batch_abs)
    return {"params_mb": round(_params_mb(full), 1), **memory_report(compiled)}


def phase_pp_interleaved(topology):
    """Interleaved 1F1B (V=2 virtual stages): 4 chunks of 3 layers on a
    pipe=2 mesh — activations circle the ring twice. Round-5 addition:
    the dryrun ran this phase on the CPU mesh only; this is its real-
    compiler certificate (round-4 verdict item 4)."""
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.parallel import (
        make_gpt2_pp_train_step,
        split_gpt2_params_interleaved,
    )

    world = topology_world({"data": 4, "pipe": 2}, topology)
    seq = 256
    cfg = GPT2Config.small(max_seq_len=seq, head_dtype=jnp.bfloat16, tie_head=False)
    model = GPT2(cfg)
    full = _abstract_params(model, jnp.zeros((1, seq), jnp.int32))
    split = jax.eval_shape(
        lambda p: split_gpt2_params_interleaved(p, cfg.num_layers, 2, 2),
        full,
    )
    init_fn, step_fn, state_specs = make_gpt2_pp_train_step(
        cfg, goo_adam(3e-4), world, num_microbatches=4, zero1=True,
        schedule="interleaved", num_chunks=2,
    )
    specs = state_specs(split)
    state = abstractify(jax.eval_shape(init_fn, split), world.mesh, specs)
    batch_abs = abstractify(
        # 32 rows / data=4 → 8 per device = 2 rows × 4 microbatches.
        {"tokens": jax.ShapeDtypeStruct((32, seq + 1), jnp.int32)},
        world.mesh,
        P("data"),
    )
    compiled = aot_compile(step_fn.build(split), state, batch_abs)
    return {
        "virtual_stages": 2,
        "params_mb": round(_params_mb(full), 1),
        **memory_report(compiled),
    }


def phase_3d_dp_tp_pp(topology):
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.parallel import (
        make_gpt2_dp_tp_pp_train_step,
        split_gpt2_params_3d,
    )

    world = topology_world({"data": 2, "model": 2, "pipe": 2}, topology)
    seq = 256
    cfg = GPT2Config.small(max_seq_len=seq, head_dtype=jnp.bfloat16, tie_head=False)
    model = GPT2(cfg)
    full = _abstract_params(model, jnp.zeros((1, seq), jnp.int32))
    split = jax.eval_shape(
        lambda p: split_gpt2_params_3d(p, cfg.num_layers, 2, 2), full
    )
    init_fn, step_fn, state_specs = make_gpt2_dp_tp_pp_train_step(
        cfg, goo_adam(3e-4), world, num_microbatches=2, zero1=True
    )
    specs = state_specs(split)
    state = abstractify(jax.eval_shape(init_fn, split), world.mesh, specs)
    batch_abs = abstractify(
        {"tokens": jax.ShapeDtypeStruct((8, seq + 1), jnp.int32)},
        world.mesh,
        P("data"),
    )
    compiled = aot_compile(step_fn.build(split), state, batch_abs)
    return {"params_mb": round(_params_mb(full), 1), **memory_report(compiled)}


def phase_3d_dp_cp_tp(topology):
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.parallel import (
        make_gpt2_dp_cp_tp_train_step,
        stack_gpt2_blocks,
    )

    world = topology_world({"data": 2, "seq": 2, "model": 2}, topology)
    seq = 512
    cfg = GPT2Config.small(max_seq_len=seq, head_dtype=jnp.bfloat16)
    model = GPT2(cfg)
    full = _abstract_params(model, jnp.zeros((1, seq), jnp.int32))
    stacked = jax.eval_shape(
        lambda p: stack_gpt2_blocks(p, cfg.num_layers, 2), full
    )
    init_fn, step_fn, state_specs = make_gpt2_dp_cp_tp_train_step(
        cfg, goo_adam(3e-4), world, zero1=True, flash=True, interpret=False
    )
    specs = state_specs(stacked)
    state = abstractify(jax.eval_shape(init_fn, stacked), world.mesh, specs)
    batch_abs = abstractify(
        {"tokens": jax.ShapeDtypeStruct((8, seq), jnp.int32)},
        world.mesh,
        P("data", "seq"),
    )
    compiled = aot_compile(step_fn.build(stacked), state, batch_abs)
    return {"params_mb": round(_params_mb(full), 1), **memory_report(compiled)}


def phase_ulysses_in_tp(topology):
    """The Ulysses seq↔head all-to-all composed INSIDE the Megatron-TP
    block on the dp×cp×tp mesh (dryrun phase 7b). Round-5 addition: its
    real-compiler certificate (round-4 verdict item 4). GPT-2 small: 12
    heads / model=2 → 6 local heads, divisible by seq=2."""
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.parallel import (
        make_gpt2_dp_cp_tp_train_step,
        stack_gpt2_blocks,
    )

    world = topology_world({"data": 2, "seq": 2, "model": 2}, topology)
    seq = 512
    cfg = GPT2Config.small(max_seq_len=seq, head_dtype=jnp.bfloat16)
    model = GPT2(cfg)
    full = _abstract_params(model, jnp.zeros((1, seq), jnp.int32))
    stacked = jax.eval_shape(
        lambda p: stack_gpt2_blocks(p, cfg.num_layers, 2), full
    )
    init_fn, step_fn, state_specs = make_gpt2_dp_cp_tp_train_step(
        cfg, goo_adam(3e-4), world, zero1=True, ulysses=True
    )
    specs = state_specs(stacked)
    state = abstractify(jax.eval_shape(init_fn, stacked), world.mesh, specs)
    batch_abs = abstractify(
        {"tokens": jax.ShapeDtypeStruct((8, seq), jnp.int32)},
        world.mesh,
        P("data", "seq"),
    )
    compiled = aot_compile(step_fn.build(stacked), state, batch_abs)
    return {"params_mb": round(_params_mb(full), 1), **memory_report(compiled)}


def phase_hybrid_dcn(topology):
    """The slice-major hybrid mesh program (dryrun phase 9), compiled by
    the real TPU compiler. ``jax.experimental.topologies`` describes a
    SINGLE slice, so the two DCN slices here are *virtual* (contiguous
    halves of the v5e:2x4 topology — ``comm.mesh._slice_groups``'s
    documented fallback): the compiled program's mesh layout, collective
    decomposition, and memory are exactly the multi-slice program's; only
    real DCN link latency is invisible at compile time (limitation noted
    in ``utils/aot.py``)."""
    import mpit_tpu
    from mpit_tpu import opt as gopt
    from mpit_tpu.models import LeNet
    from mpit_tpu.train import make_train_step
    from mpit_tpu.utils.aot import topology_devices

    world = mpit_tpu.init_hybrid(
        {"data": 8}, {"data": 2},
        devices=topology_devices(topology), set_default=False,
    )
    model = LeNet()
    params = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)))
    )["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["image"])
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(
            jnp.take_along_axis(logp, batch["label"][:, None], axis=1)
        )
        return loss, {}

    init_fn, step_fn, state_specs = make_train_step(
        loss_fn, gopt.goo(0.05, 0.9), world, zero1=True
    )
    state = abstractify(
        jax.eval_shape(init_fn, params), world.mesh, state_specs(params)
    )
    batch_abs = abstractify(
        {
            "image": jax.ShapeDtypeStruct((64, 28, 28, 1), jnp.float32),
            "label": jax.ShapeDtypeStruct((64,), jnp.int32),
        },
        world.mesh,
        P("data"),
    )
    compiled = aot_compile(step_fn.build(params), state, batch_abs)
    return {
        "virtual_slices": world.num_slices,
        "params_mb": round(_params_mb(params), 1),
        **memory_report(compiled),
    }


def phase_cp_long_context(topology):
    """Long context for real: 16k global tokens ring-sharded 8 ways
    (per-shard T=2048 — inside the flash kernel's VMEM envelope), the
    Pallas ring-flash + streaming-head CP training step compiled by the
    real TPU compiler. The capability SURVEY §6 long-context row
    promises, proven at a scale one chip could never run."""
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.parallel.cp import make_gpt2_cp_train_step

    world = topology_world({"data": 1, "seq": 8}, topology)
    t_global = 16384
    cfg = GPT2Config.small(max_seq_len=t_global, head_dtype=jnp.bfloat16)
    model = GPT2(cfg)
    params = _abstract_params(model, jnp.zeros((1, 32), jnp.int32))
    init_fn, step_fn, state_specs = make_gpt2_cp_train_step(
        cfg, goo_adam(3e-4), world, zero1=True, flash=True, interpret=False
    )
    specs = state_specs(params)
    state = abstractify(jax.eval_shape(init_fn, params), world.mesh, specs)
    batch_abs = abstractify(
        {"tokens": jax.ShapeDtypeStruct((2, t_global), jnp.int32)},
        world.mesh,
        P("data", "seq"),
    )
    compiled = aot_compile(step_fn.build(params), state, batch_abs)
    return {
        "global_tokens": t_global,
        "seq_shards": 8,
        "params_mb": round(_params_mb(params), 1),
        **memory_report(compiled),
    }


def phase_ep_moe(topology):
    from mpit_tpu.models import GPT2Config
    from mpit_tpu.models.gpt2_moe import GPT2MoE, MoESettings
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.parallel import make_gpt2_moe_train_step

    world = topology_world({"data": 2, "expert": 4}, topology)
    seq = 256
    cfg = GPT2Config.small(max_seq_len=seq, head_dtype=jnp.bfloat16)
    moe = MoESettings(num_experts=8, k=2, capacity_factor=1.25, every=2)
    model = GPT2MoE(cfg, moe)
    full = _abstract_params(model, jnp.zeros((1, seq), jnp.int32))
    init_fn, step_fn, state_specs = make_gpt2_moe_train_step(
        cfg, moe, goo_adam(3e-4), world, zero1=True
    )
    specs = state_specs(full)
    state = abstractify(jax.eval_shape(init_fn, full), world.mesh, specs)
    batch_abs = abstractify(
        {"tokens": jax.ShapeDtypeStruct((16, seq + 1), jnp.int32)},
        world.mesh,
        P(("data", "expert")),
    )
    compiled = aot_compile(step_fn.build(full), state, batch_abs)
    return {"params_mb": round(_params_mb(full), 1), **memory_report(compiled)}


def phase_pallas_ring_allreduce(topology):
    from mpit_tpu.ops import ring_allreduce

    world = topology_world({"data": 8}, topology)
    f = jax.jit(
        world.shard_map(
            lambda v: ring_allreduce(v, "data", interpret=False),
            in_specs=P("data"),
            out_specs=P("data"),
        )
    )
    x = abstractify(
        jax.ShapeDtypeStruct((8, 4 * 2**20 // 4), jnp.float32),  # 4 MiB/device
        world.mesh,
        P("data"),
    )
    compiled = aot_compile(f, x)
    return memory_report(compiled)


PHASES = [
    ("dp-zero1", phase_dp_zero1),
    ("dp-zero1-moe322m", phase_dp_zero1_moe322m),
    ("tp", phase_tp),
    ("pp-1f1b", phase_pp_1f1b),
    ("pp-interleaved-v2", phase_pp_interleaved),
    ("3d-dp-tp-pp", phase_3d_dp_tp_pp),
    ("3d-dp-cp-tp", phase_3d_dp_cp_tp),
    ("ulysses-in-tp", phase_ulysses_in_tp),
    ("cp-long-context-16k", phase_cp_long_context),
    ("ep-moe", phase_ep_moe),
    ("hybrid-dcn", phase_hybrid_dcn),
    ("pallas-ring-allreduce", phase_pallas_ring_allreduce),
]


def main(topology: str = "v5e:2x4") -> int:
    record = {"topology": topology, "phases": {}}
    failed = []
    for name, fn in PHASES:
        t0 = time.time()
        try:
            info = fn(topology)
            info["compile_seconds"] = round(time.time() - t0, 1)
            record["phases"][name] = {"ok": True, **info}
            print(
                f"compile_multichip {name}: ok "
                f"({info['compile_seconds']}s, temp "
                f"{info.get('temp_bytes', 0)/2**20:.0f} MiB)"
            )
        except Exception as e:  # noqa: BLE001 — record and continue
            failed.append(name)
            record["phases"][name] = {
                "ok": False,
                "error": f"{type(e).__name__}: {e}",
            }
            print(f"compile_multichip {name}: FAIL — {type(e).__name__}: {e}")
            traceback.print_exc()
    record["ok"] = not failed
    with open("MULTICHIP_AOT.json", "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({"ok": record["ok"], "failed": failed}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "v5e:2x4"))
