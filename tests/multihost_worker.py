"""Worker for the multi-host bootstrap e2e (tests/test_comm.py).

Launched as 2+ separate OS processes by ``TestMultiHostBootstrap``, each
with the env contract ``comm/mesh.py::_maybe_distributed_initialize``
reads (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
``JAX_PROCESS_ID``) — the TPU-native analogue of ranks launched under
``mpirun`` joining ``MPI_COMM_WORLD`` (SURVEY.md §4.1). Each process
contributes its local CPU devices; ``mpit_tpu.init()`` must come up with
the GLOBAL mesh, run a real cross-process ``psum``, and round-trip a
sharded checkpoint through orbax's multi-process path.

Prints one ``MULTIHOST_OK {...}`` JSON line on success; any assertion or
hang (the launcher enforces a timeout) fails the test.
"""

import json
import os
import sys
import time


def _flight_recorder_demo(world, pid: int, out_path: str) -> None:
    """ISSUE 3: the distributed flight recorder over the real transport.

    Each process records into its own recorder (process 1 carries an
    injected straggler phase and a known P2P counter), ships it through
    ``aggregate.gather_distributed`` (World.gather_host_bytes — a real
    cross-process collective), and process 0 persists the merged flight
    record + the per-rank-lane trace pid set for the launcher to check.
    """
    from mpit_tpu import obs
    from mpit_tpu.obs import aggregate

    rec = obs.enable(obs.Recorder())
    with obs.span("fr_compute"):
        time.sleep(0.25 if pid == 1 else 0.05)  # pid 1 = straggler
    # A known directed traffic entry per process: the merged matrix must
    # carry BOTH, though each process only recorded its own.
    obs.counter(
        "p2p_send_bytes", 1000.0 * (pid + 1),
        src=pid, dst=(pid + 1) % world.process_count,
    )
    per_rank = aggregate.gather_distributed(world, rec)
    obs.disable()
    if pid == 0:
        doc = {
            "record": aggregate.flight_record(per_rank),
            "trace_pids": sorted(
                {e["pid"] for e in aggregate.merged_trace_events(per_rank)}
            ),
        }
        with open(out_path, "w") as f:
            json.dump(doc, f)


def main() -> None:
    ckpt_dir = sys.argv[1]
    flight_record = None
    if "--flight-record" in sys.argv:
        flight_record = sys.argv[sys.argv.index("--flight-record") + 1]

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import mpit_tpu

    # init() performs jax.distributed.initialize from the env contract.
    world = mpit_tpu.init()

    n_proc = int(os.environ["JAX_NUM_PROCESSES"])
    pid = int(os.environ["JAX_PROCESS_ID"])
    assert world.process_count == n_proc, (world.process_count, n_proc)
    assert world.process_index == pid, (world.process_index, pid)
    local = world.local_devices()
    n_local = len(local)
    assert n_local >= 1
    assert world.num_devices == n_proc * n_local, (
        world.num_devices, n_proc, n_local,
    )
    assert all(d.process_index == pid for d in local)

    # One global collective across the process boundary: each device
    # contributes its global mesh position; the psum must see ALL of them.
    n = world.num_devices

    def body(x):
        return jax.lax.psum(x, "data")

    f = jax.jit(world.shard_map(body, in_specs=P("data"), out_specs=P()))
    from mpit_tpu.data import shard_batch

    x = shard_batch(world, np.arange(n, dtype=np.float32).reshape(n, 1))
    total = float(np.asarray(f(x)[0]).item())
    assert total == n * (n - 1) / 2, total

    # Checkpoint save/restore across processes (orbax multi-process path):
    # a data-sharded array must restore bit-exactly on every process.
    from mpit_tpu.train import CheckpointManager
    from mpit_tpu.train.step import TrainState

    # Every leaf must be a GLOBAL array for orbax's multi-process
    # serialization (host-local scalars are rejected) — in real training
    # the jitted init/step functions produce exactly that; here the state
    # is hand-built, so place the scalar replicated explicitly.
    from jax.sharding import NamedSharding

    state = TrainState(
        step=jax.device_put(
            jnp.asarray(3, jnp.int32), NamedSharding(world.mesh, P())
        ),
        params={"w": x},
        opt_state=(),
        extra=(),
    )
    specs = TrainState(step=P(), params={"w": P("data")}, opt_state=(), extra=())
    mgr = CheckpointManager(ckpt_dir, world, async_save=False)
    mgr.save(3, state)
    mgr.wait()
    restored = mgr.restore(state, specs)
    assert int(restored.step) == 3  # replicated: locally addressable
    # The restored w spans both processes; each process verifies exactly
    # its own addressable shards against the global ground truth.
    want = np.arange(n, dtype=np.float32).reshape(n, 1)
    shards = restored.params["w"].addressable_shards
    assert len(shards) == n_local
    for sh in shards:
        np.testing.assert_array_equal(np.asarray(sh.data), want[sh.index])

    if flight_record:
        _flight_recorder_demo(world, pid, flight_record)

    print(
        "MULTIHOST_OK "
        + json.dumps(
            {
                "process": pid,
                "n_processes": n_proc,
                "local_devices": n_local,
                "global_devices": world.num_devices,
                "psum": total,
            }
        )
    )


if __name__ == "__main__":
    main()
