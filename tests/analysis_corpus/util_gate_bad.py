"""Corpus: unlabeled-utilization fires exactly once — an MFU
percentage computed with no platform gate anywhere in the function is
a fabricated number on every non-TPU backend (the obs honesty rule)."""


def rollup(flops, seconds, peak):
    out = {"achieved_flops": flops / seconds}
    out["mfu_pct"] = 100.0 * flops / (seconds * peak)  # VIOLATION
    return out
