"""Disaggregated serving fleet: router + prefill/decode workers (ISSUE 19).

The single-process :class:`~mpit_tpu.serve.scheduler.Server` caps
concurrency at one host's slots and pages. The fleet replays the
paper's pserver request loop as inference — the MXNET-MPI task-model
shape with the collectives embedded in the serving dataflow:

- **rank 0, the router**: admits requests fleet-wide with the policy
  tier's projected-TTFT math (:class:`~mpit_tpu.serve.policy.
  TTFTProjector` over a :class:`~mpit_tpu.obs.stream.StreamRegistry`
  fed by worker tick reports), assigns each to a free prefill worker
  and the least-loaded live decode worker, and owns liveness: the
  EASGD anchor machinery's ``Probe(timeout=)`` loop + lease sweep, so
  a dead worker's in-flight requests re-queue to a survivor instead of
  hanging.
- **ranks 1..P, prefill workers**: run chunked prefill on their own
  engine (slot 0, reset per request) and ship the finished KV rows to
  the assigned decode worker as a length-prefixed
  :mod:`~mpit_tpu.serve.shipment` on the dedicated
  ``Comm_dup("fleet-kv")`` channel.
- **ranks P+1..P+D, decode workers**: admit shipments into their own
  slots/pages (paged: an all-or-nothing ``allocator.admit``; dense: a
  memledger-granted slot), inject the KV rows, and stream decode ticks
  until EOS/max-tokens, reporting completions to the router.

Every worker runs the elastic heartbeat-thread idiom (bind_thread +
the rank's own recorder); a killed worker (``FaultPlan.kill_at``)
stops its heartbeats, its lease expires at the router, and its
in-flight requests re-dispatch — greedy outputs stay bit-identical to
the single-engine run because prefill chunking and decode ticks are
deterministic per request. The flight-recorder gather discipline is
PR 3's: every rank gathers at end of job (killed workers too — the
non-root side only Sends), the router attaches the skew report and
the merged P2P matrix, on which KV shipment bytes ride (shipment
sends deliberately use the ambient recorder, unlike the obs gather's
throwaway one).

Control tags live in the 41-46 block on ``Comm_dup("fleet-ctl")``
(elastic owns 31-37, shipments 61-63 on their own channel — disjoint
matching spaces throughout). Control messages are length-prefixed
JSON: an ``int64[1]`` byte count then the ``uint8`` payload on the
same (src, tag) — compat's per-(src, tag) FIFO makes the pair safe
even under ``ANY_SOURCE`` probing.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import threading
import time
from collections import deque
from typing import Any, Callable

import numpy as np

from mpit_tpu import compat as mpiT
from mpit_tpu.obs import core as _obs
from mpit_tpu.obs.stream import StreamRegistry
from mpit_tpu.obs.trace import Ledger
from mpit_tpu.serve.policy import TTFTProjector
from mpit_tpu.serve.shipment import (
    SHIPMENT_CHANNEL,
    KVShipment,
    inject_shipment,
    recv_shipment,
    send_shipment,
)

__all__ = [
    "CTL_CHANNEL",
    "FleetConfig",
    "ROUTER_RANK",
    "parse_fleet_spec",
    "run_fleet",
]

ROUTER_RANK = 0
CTL_CHANNEL = "fleet-ctl"

# Control tags (41-46; elastic's anchor protocol owns 31-37).
TAG_ASSIGN = 41     # router -> prefill: one request assignment (json)
TAG_PREFILLED = 42  # prefill -> router: prefill done + tick cost (json)
TAG_SHIP = 43       # prefill -> decode: shipment notify (json; KV follows)
TAG_DONE = 44       # decode -> router: completion (json)
TAG_STOP = 45       # router -> worker: drain and exit (int32[1])
TAG_HB = 46         # worker -> router: heartbeat (int32[1] = progress)

_TAG_NAMES = {
    TAG_ASSIGN: "assign", TAG_PREFILLED: "prefilled", TAG_SHIP: "ship",
    TAG_DONE: "done", TAG_STOP: "stop", TAG_HB: "hb",
}


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet topology + liveness knobs. ``admission_ttft_s`` is the
    router's shed threshold on the projected TTFT (<= 0 = admit
    everything; the projector abstains while cold either way)."""

    prefill: int = 1
    decode: int = 1
    heartbeat_s: float = 0.05
    lease_s: float = 0.5
    admission_ttft_s: float = 0.0
    job_timeout_s: float = 120.0

    def __post_init__(self):
        if self.prefill < 1 or self.decode < 1:
            raise ValueError(
                f"fleet needs >=1 prefill and >=1 decode worker, got "
                f"prefill={self.prefill} decode={self.decode}"
            )
        if self.lease_s <= self.heartbeat_s:
            raise ValueError(
                f"lease_s ({self.lease_s}) must exceed heartbeat_s "
                f"({self.heartbeat_s}) or every worker flaps"
            )

    @property
    def nranks(self) -> int:
        return 1 + self.prefill + self.decode

    def role_of(self, rank: int) -> str:
        if rank == ROUTER_RANK:
            return "router"
        return "prefill" if rank <= self.prefill else "decode"


_SPEC_KEYS = {
    "prefill": int, "decode": int, "heartbeat_s": float, "lease_s": float,
    "admission_ttft_s": float, "job_timeout_s": float,
}


def parse_fleet_spec(text: str) -> FleetConfig:
    """``"prefill=2,decode=2[,lease_s=0.5,...]"`` -> FleetConfig."""
    kw: dict[str, Any] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"fleet spec field {part!r} is not key=value "
                f"(known keys: {sorted(_SPEC_KEYS)})"
            )
        key, val = part.split("=", 1)
        key = key.strip()
        conv = _SPEC_KEYS.get(key)
        if conv is None:
            raise ValueError(
                f"unknown fleet spec key {key!r} "
                f"(known: {sorted(_SPEC_KEYS)})"
            )
        kw[key] = conv(val)
    return FleetConfig(**kw)


# ---------------------------------------------------------------------------
# Length-prefixed JSON control frames.
# ---------------------------------------------------------------------------


def _send_json(obj: dict, dest: int, tag: int, comm) -> None:
    payload = np.frombuffer(json.dumps(obj).encode("utf-8"), np.uint8)
    mpiT.Send(np.asarray([payload.size], np.int64), dest=dest, tag=tag,
              comm=comm)
    mpiT.Send(payload, dest=dest, tag=tag, comm=comm)


def _recv_json(src: int, tag: int, comm) -> dict:
    """Both frames queue on one (src, tag) stream — the length prefix
    is already in flight when the caller's Probe saw it, so neither
    Recv can block against a live sender."""
    n = np.empty((1,), np.int64)
    mpiT.Recv(n, src=src, tag=tag, comm=comm)
    payload = np.empty((int(n[0]),), np.uint8)
    mpiT.Recv(payload, src=src, tag=tag, comm=comm)
    return json.loads(payload.tobytes().decode("utf-8"))


def _drain_unexpected(st, comm) -> None:
    """The pserver rule, sharpened: an unexpected tag is a protocol
    bug — fail loudly (the job aborts, so the unconsumed frame dies
    with the wire; we cannot even size a drain buffer without knowing
    the rogue sender's dtype)."""
    raise RuntimeError(
        f"fleet: unexpected tag {st.tag} from rank {st.source} "
        f"({st.count} elements)"
    )


# ---------------------------------------------------------------------------
# Heartbeats (the elastic AnchorClient idiom, verbatim shape).
# ---------------------------------------------------------------------------


def _start_heartbeats(rank: int, ctl, cfg: FleetConfig, progress):
    """Daemon thread Sending TAG_HB every ``heartbeat_s``. Returns the
    stop event; the worker sets it before exiting (a killed worker
    MUST stop beating or its lease never expires and its in-flight
    requests never re-queue)."""
    stop = threading.Event()
    rank_rec = _obs.get_recorder()

    def _beat():
        # Adopt the worker's rank identity (compat.bind_thread) AND its
        # recorder, so heartbeat sends carry the right source and are
        # charged to this rank's event stream.
        mpiT.bind_thread(rank, ctl)
        rec_ctx = (
            _obs.local_recorder(rank_rec) if rank_rec is not None
            else contextlib.nullcontext()
        )
        with rec_ctx:
            while not stop.wait(cfg.heartbeat_s):
                mpiT.Send(
                    np.asarray([progress()], np.int32),
                    dest=ROUTER_RANK, tag=TAG_HB, comm=ctl,
                )

    threading.Thread(
        target=_beat, daemon=True, name=f"fleet-hb-{rank}"
    ).start()
    return stop


# ---------------------------------------------------------------------------
# Router.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _WorkerSlot:
    role: str
    last_hb: float
    active: bool = True
    busy_rid: str | None = None      # prefill workers: current assignment
    inflight: set = dataclasses.field(default_factory=set)


def _fleet_router(requests, cfg: FleetConfig, ctl) -> dict:
    """Rank 0: admission, routing, liveness, completion collection."""
    registry = StreamRegistry()
    projector = TTFTProjector(registry)
    ledger = Ledger(mode="aggregate", origin_rank=ROUTER_RANK)

    prefill_ranks = list(range(1, 1 + cfg.prefill))
    decode_ranks = list(range(1 + cfg.prefill, cfg.nranks))
    now = time.monotonic()
    slots = {
        r: _WorkerSlot(cfg.role_of(r), now)
        for r in prefill_ranks + decode_ranks
    }

    reqs = {str(r.rid): r for r in requests}
    if len(reqs) != len(requests):
        raise ValueError("fleet requests must carry unique rids")
    pending = deque(str(r.rid) for r in requests)
    decode_of: dict[str, int] = {}
    results: dict[str, list[int]] = {}
    shed: list[str] = []
    events: list[tuple] = []
    requeues = 0
    projected_last: float | None = None

    def _active(role: str) -> list[int]:
        return [
            r for r in (prefill_ranks if role == "prefill" else decode_ranks)
            if slots[r].active
        ]

    def _note(kind: str, rank: int, **extra):
        events.append((kind, rank, *extra.values()))
        _obs.instant(f"fleet_{kind}", rank=rank, **extra)

    def _gauges():
        for r, s in slots.items():
            _obs.gauge("fleet_inflight", len(s.inflight), rank=r)
        registry.set_gauge("fleet_pending", len(pending))

    order_index = {rid: i for i, rid in enumerate(pending)}

    def _requeue_one(rid: str, from_rank: int):
        nonlocal requeues
        if rid in results or rid in shed:
            return
        pending.appendleft(rid)
        decode_of.pop(rid, None)
        ledger.event(rid, "fleet_requeue", from_rank=from_rank)
        requeues += 1

    def _requeue_inflight(rank: int):
        s = slots[rank]
        # Front of the queue keeps submission order: appendleft in
        # REVERSE submission order so the earliest rid re-dispatches
        # first.
        for rid in sorted(
            s.inflight, key=lambda r: order_index.get(r, 0), reverse=True
        ):
            _requeue_one(rid, rank)
        s.inflight.clear()
        s.busy_rid = None

    # An eviction is a *suspicion*, not a death certificate: a live
    # worker descheduled past the lease (host-wide CPU stall) rejoins
    # on its next heartbeat. So an empty decode roster only aborts the
    # job after staying empty a FULL extra lease window — long enough
    # for every spuriously-evicted survivor to beat again, short
    # enough that a genuinely dead fleet still fails fast.
    decode_dead_since: list[float | None] = [None]

    def _sweep(t_now: float):
        for rank, s in slots.items():
            age = t_now - s.last_hb
            _obs.gauge("fleet_heartbeat_age_s", round(age, 4), rank=rank)
            if s.active and age > cfg.lease_s:
                s.active = False
                _note("evicted", rank, heartbeat_age_s=round(age, 4))
                _requeue_inflight(rank)
        if _active("decode") or not (pending or _unfinished()):
            decode_dead_since[0] = None
        elif decode_dead_since[0] is None:
            decode_dead_since[0] = t_now
        elif t_now - decode_dead_since[0] > cfg.lease_s:
            raise RuntimeError(
                "fleet: every decode worker's lease expired with "
                f"{len(pending)} request(s) outstanding — nothing left "
                "to re-queue onto"
            )

    def _unfinished() -> int:
        return len(reqs) - len(results) - len(shed)

    def _dispatch():
        nonlocal projected_last
        while pending:
            free_pf = [
                r for r in _active("prefill") if slots[r].busy_rid is None
            ]
            live_dec = _active("decode")
            if not free_pf or not live_dec:
                return
            rid = pending.popleft()
            req = reqs[rid]
            projected_last = projector.projected_ttft_s(len(pending))
            if (
                cfg.admission_ttft_s > 0.0
                and projected_last is not None
                and projected_last > cfg.admission_ttft_s
            ):
                shed.append(rid)
                registry.inc("fleet_shed")
                ledger.event(rid, "fleet_shed",
                             projected_ttft_s=projected_last)
                continue
            pf = free_pf[0]
            dec = min(live_dec, key=lambda r: (len(slots[r].inflight), r))
            slots[pf].busy_rid = rid
            slots[pf].inflight.add(rid)
            slots[dec].inflight.add(rid)
            decode_of[rid] = dec
            ledger.event(rid, "fleet_assign", prefill=pf, decode=dec)
            _send_json(
                {
                    "rid": rid,
                    "prompt": [int(t) for t in req.prompt],
                    "max_new_tokens": int(req.max_new_tokens),
                    "temperature": float(req.temperature),
                    "top_k": int(req.top_k),
                    "eos_id": None if req.eos_id is None else int(req.eos_id),
                    "decode": dec,
                },
                pf, TAG_ASSIGN, ctl,
            )

    probe_timeout = max(min(cfg.lease_s / 4, cfg.heartbeat_s), 0.005)
    while _unfinished():
        _dispatch()
        _gauges()
        try:
            with _obs.span("fleet:probe_wait"):
                st = mpiT.Probe(
                    mpiT.ANY_SOURCE, mpiT.ANY_TAG, comm=ctl,
                    timeout=probe_timeout,
                )
        except mpiT.CompatTimeoutError:
            _sweep(time.monotonic())
            continue
        now = time.monotonic()
        _obs.counter(
            "fleet_msgs", 1, kind=_TAG_NAMES.get(st.tag, str(st.tag))
        )
        if st.tag == TAG_HB:
            mpiT.Recv(np.empty((1,), np.int32), src=st.source, tag=TAG_HB,
                      comm=ctl)
            s = slots[st.source]
            s.last_hb = now
            if not s.active:
                s.active = True
                _note("rejoined", st.source)
        elif st.tag == TAG_PREFILLED:
            msg = _recv_json(st.source, TAG_PREFILLED, ctl)
            rid = msg["rid"]
            registry.observe("prefill_tick", float(msg["prefill_s"]))
            s = slots[st.source]
            if s.busy_rid == rid:
                s.busy_rid = None
            s.inflight.discard(rid)
            ledger.event(rid, "fleet_prefilled", rank=st.source,
                         bytes=int(msg.get("bytes", 0)))
            dec = decode_of.get(rid)
            if dec is not None and not slots[dec].active:
                # Shipped into a dead worker's void — re-queue now
                # rather than wait for the sweep to notice.
                slots[dec].inflight.discard(rid)
                _requeue_one(rid, dec)
        elif st.tag == TAG_DONE:
            msg = _recv_json(st.source, TAG_DONE, ctl)
            rid = msg["rid"]
            slots[st.source].inflight.discard(rid)
            if rid in results:
                continue  # duplicate from an evicted-then-finished worker
            results[rid] = [int(t) for t in msg["tokens"]]
            for s_tick in msg.get("decode_tick_s", []):
                registry.observe("decode_tick", float(s_tick))
            registry.inc("fleet_completed")
            ledger.event(rid, "fleet_done", rank=st.source,
                         ticks=int(msg.get("ticks", 0)))
        elif st.tag == TAG_STOP:
            # Workers never send STOP; treat as protocol corruption.
            _drain_unexpected(st, ctl)
        else:
            _drain_unexpected(st, ctl)
        _sweep(now)

    def _requeue_inflight_one(rid: str, from_rank: int):
        nonlocal requeues
        if rid in results or rid in shed:
            return
        pending.appendleft(rid)
        decode_of.pop(rid, None)
        ledger.event(rid, "fleet_requeue", from_rank=from_rank)
        requeues += 1

    for rank in prefill_ranks + decode_ranks:
        mpiT.Send(np.asarray([0], np.int32), dest=rank, tag=TAG_STOP,
                  comm=ctl)
    evictions = sum(1 for e in events if e[0] == "evicted")
    return {
        "role": "router",
        "completed": results,
        "shed": shed,
        "events": events,
        "evictions": evictions,
        "requeues": requeues,
        "projected_ttft_s_last": projected_last,
        "ledger_counts": dict(ledger.counts),
    }


# ---------------------------------------------------------------------------
# Prefill worker.
# ---------------------------------------------------------------------------


def _prefill_one(engine, msg: dict, ledger) -> tuple[KVShipment, float]:
    """Run one request's prefill on slot 0 of a freshly-reset engine
    and package the shipment. Paged engines replay the scheduler's
    chunked-prefill host loop exactly (same chunk widths → identical
    KV rows → the decode side bit-matches the single-engine run);
    dense engines take the whole prompt in one call."""
    rid = msg["rid"]
    prompt = [int(t) for t in msg["prompt"]]
    engine.reset()
    S = engine.slots
    temp = np.zeros((S,), np.float32)
    topk = np.zeros((S,), np.int32)
    temp[0] = float(msg["temperature"])
    topk[0] = int(msg["top_k"])
    t0 = time.perf_counter()
    if engine.paged:
        plan = engine.allocator.admit(0, prompt, 1, owner=rid, tick=0)
        if plan is None:
            raise RuntimeError(
                f"fleet prefill worker cannot page prompt of {len(prompt)} "
                "tokens — size the worker's kv_pages for the trace"
            )
        w = engine.prefill_chunk
        base, first = 0, None
        while base < len(prompt):
            n = min(w, len(prompt) - base)
            tk = np.zeros((S, w), np.int32)
            tk[0, :n] = prompt[base : base + n]
            ba = np.zeros((S,), np.int32)
            ba[0] = base
            cl = np.zeros((S,), np.int32)
            cl[0] = n
            fl = np.zeros((S,), np.int32)
            sm = np.zeros((S,), bool)
            sm[0] = base + n == len(prompt)
            out = engine.prefill_paged(tk, ba, cl, fl, sm, temp, topk)
            if sm[0]:
                first = int(out[0])
            base += n
    else:
        if len(prompt) > engine.prefill_len:
            raise RuntimeError(
                f"fleet dense prefill worker caps prompts at "
                f"{engine.prefill_len} tokens, got {len(prompt)}"
            )
        toks = np.zeros((S, engine.prefill_len), np.int32)
        toks[0, : len(prompt)] = prompt
        lens = np.ones((S,), np.int32)
        lens[0] = len(prompt)
        admit = np.zeros((S,), bool)
        admit[0] = True
        first = int(engine.prefill(toks, lens, admit, temp, topk)[0])
    prefill_s = time.perf_counter() - t0
    k, v = engine.export_kv_rows(0, len(prompt))
    ledger.event(rid, "fleet_prefill", dur_s=prefill_s)
    return KVShipment(
        rid=rid,
        prompt=prompt,
        first_token=first,
        length=len(prompt),
        max_new_tokens=int(msg["max_new_tokens"]),
        temperature=float(msg["temperature"]),
        top_k=int(msg["top_k"]),
        eos_id=msg["eos_id"],
        quantized=hasattr(k, "q"),
        k=k,
        v=v,
    ), prefill_s


def _prefill_worker(rank, engine_factory, cfg: FleetConfig, fault_plan,
                    ctl, kv):
    ledger = Ledger(mode="aggregate", origin_rank=rank)
    step = 0
    # Heartbeats start BEFORE the engine builds: compiles can outlast
    # the lease, and a worker evicted while warming up never serves.
    hb_stop = _start_heartbeats(rank, ctl, cfg, lambda: step)
    processed, ship_bytes, killed = 0, 0, False
    try:
        engine = engine_factory("prefill", rank)
        while True:
            if fault_plan is not None:
                fault_plan.step_action(rank, step)
            try:
                st = mpiT.Probe(
                    ROUTER_RANK, mpiT.ANY_TAG, comm=ctl,
                    timeout=cfg.heartbeat_s,
                )
            except mpiT.CompatTimeoutError:
                continue
            if st.tag == TAG_STOP:
                mpiT.Recv(np.empty((1,), np.int32), src=ROUTER_RANK,
                          tag=TAG_STOP, comm=ctl)
                break
            if st.tag != TAG_ASSIGN:
                _drain_unexpected(st, ctl)
            msg = _recv_json(ROUTER_RANK, TAG_ASSIGN, ctl)
            with _obs.span("fleet:prefill", rid=msg["rid"]):
                ship, prefill_s = _prefill_one(engine, msg, ledger)
            dec = int(msg["decode"])
            # KV frames go out BEFORE the notify: the decode worker's
            # recv_shipment finds them already FIFO-queued.
            nbytes = send_shipment(ship, dec, kv, ledger=ledger)
            _send_json({"rid": ship.rid, "src": rank}, dec, TAG_SHIP, ctl)
            _send_json(
                {
                    "rid": ship.rid,
                    "decode": dec,
                    "prefill_s": prefill_s,
                    "bytes": nbytes,
                },
                ROUTER_RANK, TAG_PREFILLED, ctl,
            )
            ship_bytes += nbytes
            processed += 1
            step += 1
    except mpiT.ReplicaKilled as death:
        killed = True
        _obs.instant("fleet_worker_killed", rank=rank, step=death.step)
    finally:
        hb_stop.set()
    return {
        "role": "prefill",
        "rank": rank,
        "worker_id": f"prefill-{rank}",
        "processed": processed,
        "ship_bytes": ship_bytes,
        "killed": killed,
        "ledger_counts": dict(ledger.counts),
    }


# ---------------------------------------------------------------------------
# Decode worker.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _DecodeLive:
    rid: str
    tokens: list
    max_new_tokens: int
    eos_id: int | None
    temperature: float
    top_k: int
    tick_s: list


def _decode_worker(rank, engine_factory, cfg: FleetConfig, fault_plan,
                   ctl, kv):
    ledger = Ledger(mode="aggregate", origin_rank=rank)
    ticks = 0
    # Heartbeats first, engine second — same warm-up rule as prefill.
    hb_stop = _start_heartbeats(rank, ctl, cfg, lambda: ticks)
    engine = engine_factory("decode", rank)
    S = engine.slots
    free = deque(range(S))
    live: dict[int, _DecodeLive] = {}
    backlog: deque[KVShipment] = deque()
    completed, killed, stop = 0, False, False

    def _finish(slot: int):
        nonlocal completed
        lv = live.pop(slot)
        if engine.paged:
            engine.allocator.free_slot(slot)
        else:
            engine.memledger.free("kv_slots", engine.slot_bytes,
                                  owner=lv.rid, kind="retire")
            engine.memledger.forget(lv.rid)
        free.append(slot)
        _send_json(
            {
                "rid": lv.rid,
                "tokens": lv.tokens,
                "ticks": len(lv.tick_s),
                "decode_tick_s": lv.tick_s,
            },
            ROUTER_RANK, TAG_DONE, ctl,
        )
        ledger.event(lv.rid, "fleet_decode_done", tokens=len(lv.tokens))
        completed += 1

    def _admit(ship: KVShipment) -> bool:
        if not free:
            return False
        slot = free[0]
        if engine.paged:
            plan = engine.allocator.admit(
                slot, ship.prompt, ship.max_new_tokens, owner=ship.rid,
                tick=ticks,
            )
            if plan is None:
                return False  # pool full — stays in backlog
        else:
            engine.memledger.grant(
                "kv_slots", engine.slot_bytes, owner=ship.rid,
                tick=ticks, kind="admit",
            )
        free.popleft()
        inject_shipment(engine, slot, ship, ledger=ledger)
        live[slot] = _DecodeLive(
            rid=ship.rid,
            tokens=[int(ship.first_token)],
            max_new_tokens=int(ship.max_new_tokens),
            eos_id=ship.eos_id,
            temperature=float(ship.temperature),
            top_k=int(ship.top_k),
            tick_s=[],
        )
        if (
            len(live[slot].tokens) >= live[slot].max_new_tokens
            or (ship.eos_id is not None
                and int(ship.first_token) == int(ship.eos_id))
        ):
            _finish(slot)
        return True

    try:
        while not stop or live or backlog:
            # Drain control frames without starving live decodes.
            timeout = 0.001 if (live or backlog) else cfg.heartbeat_s
            while True:
                try:
                    st = mpiT.Probe(
                        mpiT.ANY_SOURCE, mpiT.ANY_TAG, comm=ctl,
                        timeout=timeout,
                    )
                except mpiT.CompatTimeoutError:
                    break
                if st.tag == TAG_STOP:
                    mpiT.Recv(np.empty((1,), np.int32), src=st.source,
                              tag=TAG_STOP, comm=ctl)
                    stop = True
                elif st.tag == TAG_SHIP:
                    note = _recv_json(st.source, TAG_SHIP, ctl)
                    ship = recv_shipment(
                        int(note["src"]), kv,
                        timeout=max(cfg.lease_s * 10, 1.0), ledger=ledger,
                    )
                    backlog.append(ship)
                else:
                    _drain_unexpected(st, ctl)
                timeout = 0.001
                if stop:
                    break
            for _ in range(len(backlog)):
                if not _admit(backlog[0]):
                    break
                backlog.popleft()
            if backlog and not live and len(free) == S:
                # An empty engine refused the shipment — no amount of
                # draining will ever fit it; fail instead of spinning.
                raise RuntimeError(
                    f"fleet decode worker {rank}: shipment for "
                    f"{backlog[0].rid!r} ({backlog[0].length} rows + "
                    f"{backlog[0].max_new_tokens} new) cannot fit an "
                    "idle engine — size kv_pages/max_len for the trace"
                )
            if not live:
                if stop and not backlog:
                    break
                continue
            if fault_plan is not None:
                fault_plan.step_action(rank, ticks)
            active = np.zeros((S,), bool)
            temp = np.zeros((S,), np.float32)
            topk = np.zeros((S,), np.int32)
            for slot, lv in live.items():
                active[slot] = True
                temp[slot] = lv.temperature
                topk[slot] = lv.top_k
            t0 = time.perf_counter()
            with _obs.span("fleet:decode_tick", live=len(live)):
                nxt = engine.decode(active, temp, topk)
            dt = time.perf_counter() - t0
            ticks += 1
            for slot in sorted(live):
                lv = live[slot]
                tok = int(nxt[slot])
                lv.tokens.append(tok)
                lv.tick_s.append(dt / max(len(live), 1))
                if len(lv.tokens) >= lv.max_new_tokens or (
                    lv.eos_id is not None and tok == int(lv.eos_id)
                ):
                    _finish(slot)
    except mpiT.ReplicaKilled as death:
        killed = True
        _obs.instant("fleet_worker_killed", rank=rank, step=death.step)
    finally:
        hb_stop.set()
    return {
        "role": "decode",
        "rank": rank,
        "worker_id": f"decode-{rank}",
        "completed": completed,
        "ticks": ticks,
        "killed": killed,
        "ledger_counts": dict(ledger.counts),
    }


# ---------------------------------------------------------------------------
# Launcher (the run_elastic shape: wrap, gather, assemble).
# ---------------------------------------------------------------------------


def run_fleet(
    engine_factory: Callable[[str, int], Any],
    requests,
    *,
    prefill: int = 1,
    decode: int = 1,
    heartbeat_s: float = 0.05,
    lease_s: float = 0.5,
    admission_ttft_s: float = 0.0,
    fault_plan=None,
    flight: bool = True,
    job_timeout_s: float = 120.0,
) -> dict:
    """Launch the disaggregated fleet: 1 router + ``prefill`` +
    ``decode`` workers on the compat layer (the ``mpirun -n P`` shape).

    Args:
      engine_factory: ``(role, rank) -> Engine`` — called once per
        worker rank with role ``"prefill"`` or ``"decode"``. Workers
        need engines built from the SAME params/config for outputs to
        bit-match the single-engine run (prefill chunk width included:
        identical chunking → identical KV rows shipped).
      requests: iterable of :class:`~mpit_tpu.serve.scheduler.Request`
        with unique rids.
      fault_plan: seeded :class:`~mpit_tpu.compat.faults.FaultPlan` —
        ``kill_at={rank: step}`` kills a worker at its Nth unit of work
        (prefill: requests processed; decode: ticks run); the router's
        lease sweep re-queues its in-flight requests.
      flight: per-rank recorders + end-of-job gather — the result's
        ``flight`` block carries the skew report and the merged P2P
        matrix (KV shipment bytes ride it).

    Returns ``{"router": {...}, "workers": [...], "completed":
    {rid: tokens}, "shed": [...], "flight": {...}, "fault_events":
    (...)}``.
    """
    cfg = FleetConfig(
        prefill=prefill, decode=decode, heartbeat_s=heartbeat_s,
        lease_s=lease_s, admission_ttft_s=admission_ttft_s,
        job_timeout_s=job_timeout_s,
    )
    from mpit_tpu.obs import aggregate

    req_list = list(requests)

    def main(rank: int):
        rec_ctx = (
            _obs.local_recorder(_obs.Recorder()) if flight
            else contextlib.nullcontext()
        )
        with rec_ctx:
            ctl = mpiT.Comm_dup(None, key=CTL_CHANNEL)
            kv = mpiT.Comm_dup(None, key=SHIPMENT_CHANNEL)
            role = cfg.role_of(rank)
            if role == "router":
                out = _fleet_router(req_list, cfg, ctl)
            elif role == "prefill":
                out = _prefill_worker(
                    rank, engine_factory, cfg, fault_plan, ctl, kv,
                )
            else:
                out = _decode_worker(
                    rank, engine_factory, cfg, fault_plan, ctl, kv,
                )
            per_rank = (
                aggregate.gather_compat(root=ROUTER_RANK) if flight else None
            )
        if rank == ROUTER_RANK and per_rank is not None:
            out["_flight"] = {
                "skew": aggregate.skew_report(per_rank),
                "record": aggregate.flight_record(per_rank),
                "p2p_bytes": aggregate.merged_matrix(
                    per_rank, counter="p2p_send_bytes"
                ),
            }
        return out

    results = mpiT.run(
        main, cfg.nranks, pass_rank=True, timeout=job_timeout_s,
        fault_plan=fault_plan,
    )
    router = results[ROUTER_RANK]
    flight_doc = router.pop("_flight", None)
    out = {
        "router": router,
        "workers": results[1:],
        "completed": router["completed"],
        "shed": router["shed"],
    }
    if flight_doc is not None:
        out["flight"] = flight_doc
    if fault_plan is not None:
        out["fault_events"] = fault_plan.events()
    return out
