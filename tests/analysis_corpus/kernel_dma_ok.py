"""Corpus false-positive guard: both repo DMA disciplines are clean —
the descriptor-recreation double buffer (flash-decode) and the
started/waited list (_Ring.exchange)."""


# analysis: pallas-kernel
def double_buffered(x_hbm, o_ref, buf, sem, pl, pltpu, n_k):
    def dma(src, slot, ki):
        return pltpu.make_async_copy(src.at[ki], buf.at[slot], sem.at[slot])

    dma(x_hbm, 0, 0).start()

    def body(ki, acc):
        slot = ki % 2

        @pl.when(ki + 1 < n_k)
        def _prefetch():
            dma(x_hbm, 1 - slot, ki + 1).start()

        dma(x_hbm, slot, ki).wait()
        return acc + buf[slot].sum()

    o_ref[...] = body(0, 0.0)


# analysis: pallas-kernel
def list_discipline(sbuf, rbuf, ssem, rsem, pltpu):
    rdmas = []
    rdmas.append(pltpu.make_async_remote_copy(sbuf, rbuf, ssem, rsem))
    for r in rdmas:
        r.start()
    for r in rdmas:
        r.wait()
