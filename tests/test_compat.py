"""Tests for the mpiT compat facade (host-level multi-rank simulator).

Mirrors the reference's own test strategy (SURVEY.md §5.1): small programs
run under "mpirun -n 2..4" exercising tensor send/recv, async requests with
Wait/Test, and collectives — here ``compat.run`` is the mpirun analogue.
"""

from __future__ import annotations

import numpy as np
import pytest

from mpit_tpu import compat as mpiT


def test_world_of_one_without_run():
    # A bare script outside run() is a world of one (no-mpirun behavior).
    mpiT.Init()
    assert mpiT.Initialized()
    assert mpiT.Comm_size(mpiT.COMM_WORLD) == 1
    assert mpiT.Comm_rank(mpiT.COMM_WORLD) == 0
    mpiT.Finalize()
    assert not mpiT.Initialized()


def test_rank_size_under_run():
    def main():
        mpiT.Init()
        return mpiT.Comm_rank(mpiT.COMM_WORLD), mpiT.Comm_size(mpiT.COMM_WORLD)

    out = mpiT.run(main, 4)
    assert out == [(r, 4) for r in range(4)]


def test_blocking_send_recv_ring():
    """Each rank sends its payload to (rank+1)%n — the ring smoke test."""
    n = 4

    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        payload = np.full((3,), float(r), np.float64)
        buf = np.zeros((3,), np.float64)
        if r % 2 == 0:  # stagger to avoid symmetric blocking assumptions
            mpiT.Send(payload, dest=(r + 1) % n, tag=7)
            mpiT.Recv(buf, src=(r - 1) % n, tag=7)
        else:
            mpiT.Recv(buf, src=(r - 1) % n, tag=7)
            mpiT.Send(payload, dest=(r + 1) % n, tag=7)
        return buf.copy()

    out = mpiT.run(main, n)
    for r in range(n):
        np.testing.assert_array_equal(out[r], np.full((3,), float((r - 1) % n)))


def test_isend_irecv_wait():
    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        if r == 0:
            req = mpiT.Isend(np.arange(5, dtype=np.float32), dest=1, tag=3)
            mpiT.Wait(req)
            return None
        buf = np.zeros(5, np.float32)
        req = mpiT.Irecv(buf, src=0, tag=3)
        status = mpiT.Wait(req)
        assert status.source == 0 and status.tag == 3 and status.count == 5
        return buf

    out = mpiT.run(main, 2)
    np.testing.assert_array_equal(out[1], np.arange(5, dtype=np.float32))


def test_test_polling():
    import threading

    release = threading.Event()

    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        if r == 0:
            release.wait(10)
            mpiT.Send(np.ones(2), dest=1, tag=1)
            return None
        buf = np.zeros(2)
        req = mpiT.Irecv(buf, src=0, tag=1)
        assert not mpiT.Test(req)  # nothing sent yet
        release.set()
        while not mpiT.Test(req):
            pass
        return buf

    out = mpiT.run(main, 2)
    np.testing.assert_array_equal(out[1], np.ones(2))


def test_any_source_server_loop():
    """The pserver pattern (SURVEY.md §4.2): one server rank receives from
    ANY_SOURCE, dispatches on tag, replies to status.source."""
    n = 4
    TAG_GRAD, TAG_REPLY = 1, 2

    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        if r == 0:  # server: accumulate one grad from each client
            acc = np.zeros(2)
            for _ in range(n - 1):
                buf = np.zeros(2)
                st = mpiT.Recv(buf, src=mpiT.ANY_SOURCE, tag=TAG_GRAD)
                acc += buf
                mpiT.Send(acc.copy(), dest=st.source, tag=TAG_REPLY)
            return acc
        mpiT.Send(np.full(2, float(r)), dest=0, tag=TAG_GRAD)
        buf = np.zeros(2)
        mpiT.Recv(buf, src=0, tag=TAG_REPLY)
        return buf

    out = mpiT.run(main, n)
    np.testing.assert_array_equal(out[0], np.full(2, 1.0 + 2.0 + 3.0))


def test_tag_matching_fifo_and_wildcards():
    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        if r == 0:
            mpiT.Send(np.array([1.0]), dest=1, tag=10)
            mpiT.Send(np.array([2.0]), dest=1, tag=20)
            mpiT.Send(np.array([3.0]), dest=1, tag=10)
            return None
        buf = np.zeros(1)
        mpiT.Recv(buf, src=0, tag=20)  # out-of-order tag match
        a = buf[0]
        st = mpiT.Probe(src=mpiT.ANY_SOURCE, tag=mpiT.ANY_TAG)
        assert st.tag == 10
        mpiT.Recv(buf, src=0, tag=10)  # FIFO within (src, tag)
        b = buf[0]
        mpiT.Recv(buf, src=mpiT.ANY_SOURCE, tag=mpiT.ANY_TAG)
        c = buf[0]
        return (a, b, c)

    out = mpiT.run(main, 2)
    assert out[1] == (2.0, 1.0, 3.0)


def test_posted_receive_matching_order():
    """MPI posted-receive semantics: a message is routed to the earliest
    posted matching receive at arrival time, regardless of Wait order."""
    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        if r == 0:
            mpiT.Barrier()  # let rank 1 post both receives first
            mpiT.Send(np.array([1.0]), dest=1, tag=1)
            mpiT.Send(np.array([2.0]), dest=1, tag=2)
            return None
        buf_a = np.zeros(1)
        buf_b = np.zeros(1)
        req_a = mpiT.Irecv(buf_a, src=0, tag=1)
        req_b = mpiT.Irecv(buf_b, src=0, tag=mpiT.ANY_TAG)
        mpiT.Barrier()
        mpiT.Wait(req_b)  # waiting on B first must not steal A's message
        mpiT.Wait(req_a)
        assert req_a.status.tag == 1 and req_b.status.tag == 2
        return (buf_a[0], buf_b[0])

    out = mpiT.run(main, 2)
    assert out[1] == (1.0, 2.0)


def test_bcast():
    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        buf = np.full(4, float(r) if r == 2 else -1.0)
        mpiT.Bcast(buf, root=2)
        return buf

    for row in mpiT.run(main, 4):
        np.testing.assert_array_equal(row, np.full(4, 2.0))


@pytest.mark.parametrize(
    "op,expect", [(mpiT.SUM, 6.0), (mpiT.MAX, 3.0), (mpiT.MIN, 0.0), (mpiT.PROD, 0.0)]
)
def test_allreduce_ops(op, expect):
    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        recv = np.zeros(2)
        mpiT.Allreduce(np.full(2, float(r)), recv, op=op)
        return recv

    for row in mpiT.run(main, 4):
        np.testing.assert_array_equal(row, np.full(2, expect))


def test_reduce_root_only():
    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        out = mpiT.Reduce(np.full(2, float(r)), op=mpiT.SUM, root=1)
        return None if out is None else out.copy()

    out = mpiT.run(main, 3)
    assert out[0] is None and out[2] is None
    np.testing.assert_array_equal(out[1], np.full(2, 3.0))


def test_barrier_collective_reuse():
    # Repeated collectives on the same communicator must not corrupt slots.
    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        total = 0.0
        for i in range(5):
            mpiT.Barrier()
            total += float(mpiT.Allreduce(np.array([float(r + i)]))[0])
        return total

    out = mpiT.run(main, 4)
    # sum over ranks of (r+i) for i in 0..4 = (0+1+2+3) + 4*i each round
    expect = sum(6.0 + 4.0 * i for i in range(5))
    assert all(abs(v - expect) < 1e-9 for v in out)


def test_rank_failure_propagates():
    def main():
        mpiT.Init()
        if mpiT.Comm_rank(mpiT.COMM_WORLD) == 1:
            raise RuntimeError("rank 1 died")
        mpiT.Barrier()  # would hang forever without abort propagation

    with pytest.raises(RuntimeError, match="rank 1 died"):
        mpiT.run(main, 3, timeout=30)


def test_rank_failure_wakes_blocked_recv():
    """A dead rank must abort peers parked in a blocking Recv (not just in a
    barrier) and surface the root-cause error, without waiting for timeout."""
    import time

    def main():
        mpiT.Init()
        if mpiT.Comm_rank(mpiT.COMM_WORLD) == 1:
            raise RuntimeError("rank 1 died before sending")
        buf = np.zeros(2)
        mpiT.Recv(buf, src=1, tag=0)  # never satisfied

    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="rank 1 died before sending"):
        mpiT.run(main, 2, timeout=60)
    assert time.monotonic() - t0 < 10  # aborted promptly, not via timeout


def test_recv_dtype_mismatch_raises():
    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        if r == 0:
            mpiT.Send(np.ones(2, np.float64), dest=1, tag=0)
            return
        buf = np.zeros(2, np.int32)
        mpiT.Recv(buf, src=0, tag=0)

    with pytest.raises(TypeError, match="dtype"):
        mpiT.run(main, 2, timeout=30)


def test_collective_buffer_reuse_after_return():
    """MPI contract: the send buffer is the caller's again once the call
    returns — immediate mutation must not corrupt slower peers' results."""
    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        ok = True
        for i in range(50):
            g = np.full(4, float(r + i))
            out = mpiT.Allreduce(g)
            g[...] = -1e9  # mutate immediately after return
            ok &= bool(np.all(out == sum(float(q + i) for q in range(4))))
        return ok

    assert all(mpiT.run(main, 4))


class TestTimeouts:
    """ISSUE 11 satellite: ``Recv``/``Wait``/``Probe`` grow ``timeout=``
    raising a structured :class:`CompatTimeoutError` (peer rank + tag)
    instead of blocking forever on a dead peer."""

    def test_recv_timeout_carries_envelope(self):
        def main():
            mpiT.Init()
            r = mpiT.Comm_rank(mpiT.COMM_WORLD)
            if r == 1:
                return None  # never sends
            try:
                mpiT.Recv(np.zeros(2), src=1, tag=7, timeout=0.05)
            except mpiT.CompatTimeoutError as e:
                return (e.op, e.rank, e.src, e.tag)

        out = mpiT.run(main, 2, timeout=30)
        assert out[0] == ("Recv", 0, 1, 7)

    def test_recv_timeout_withdraws_posted_receive(self):
        """After a timed-out Recv, a late message must NOT land in the
        abandoned buffer — it queues as unexpected and a fresh Recv
        gets it."""
        import threading

        sent = threading.Event()

        def main():
            mpiT.Init()
            r = mpiT.Comm_rank(mpiT.COMM_WORLD)
            if r == 1:
                sent.wait(10)
                mpiT.Send(np.asarray([5.0]), dest=0, tag=3)
                return None
            stale = np.zeros(1)
            with pytest.raises(mpiT.CompatTimeoutError):
                mpiT.Recv(stale, src=1, tag=3, timeout=0.05)
            sent.set()
            fresh = np.zeros(1)
            mpiT.Recv(fresh, src=1, tag=3, timeout=5.0)
            return (float(stale[0]), float(fresh[0]))

        out = mpiT.run(main, 2, timeout=30)
        assert out[0] == (0.0, 5.0)

    def test_wait_timeout_then_retry_succeeds(self):
        import threading

        release = threading.Event()

        def main():
            mpiT.Init()
            r = mpiT.Comm_rank(mpiT.COMM_WORLD)
            if r == 1:
                release.wait(10)
                mpiT.Send(np.ones(2), dest=0, tag=1)
                return None
            buf = np.zeros(2)
            req = mpiT.Irecv(buf, src=1, tag=1)
            with pytest.raises(mpiT.CompatTimeoutError):
                mpiT.Wait(req, timeout=0.05)
            release.set()
            # The request stayed posted: the retry completes it — the
            # anchor client's retry/backoff is built on exactly this.
            st = mpiT.Wait(req, timeout=5.0)
            assert st.source == 1
            return buf.copy()

        out = mpiT.run(main, 2, timeout=30)
        np.testing.assert_array_equal(out[0], np.ones(2))

    def test_probe_timeout(self):
        def main():
            mpiT.Init()
            if mpiT.Comm_rank(mpiT.COMM_WORLD) == 1:
                return None
            with pytest.raises(mpiT.CompatTimeoutError) as ei:
                mpiT.Probe(mpiT.ANY_SOURCE, mpiT.ANY_TAG, timeout=0.05)
            return (ei.value.op, "any" in str(ei.value))

        out = mpiT.run(main, 2, timeout=30)
        assert out[0] == ("Probe", True)

    def test_no_timeout_still_blocks_until_delivery(self):
        def main():
            mpiT.Init()
            r = mpiT.Comm_rank(mpiT.COMM_WORLD)
            if r == 1:
                import time

                time.sleep(0.1)
                mpiT.Send(np.asarray([9.0]), dest=0, tag=2)
                return None
            buf = np.zeros(1)
            mpiT.Recv(buf, src=1, tag=2, timeout=10.0)
            return float(buf[0])

        out = mpiT.run(main, 2, timeout=30)
        assert out[0] == 9.0


def test_job_timeout_dumps_mailbox_state(capfd):
    """Deadlock watchdog (ISSUE 11 satellite): a timed-out job dumps
    every rank's mailbox state (pending/posted envelopes) to stderr
    before aborting, so a hang names the stuck cycle."""

    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        if r == 0:
            mpiT.Send(np.ones(1), dest=1, tag=42)  # unexpected at rank 1
            return None
        mpiT.Recv(np.zeros(1), src=0, tag=99)  # never satisfied: deadlock

    with pytest.raises(TimeoutError):
        mpiT.run(main, 2, timeout=1.0)
    err = capfd.readouterr().err
    assert "per-rank mailbox state" in err
    assert '"tag": 42' in err  # the pending unexpected message
    assert '"tag": 99' in err  # the posted never-matched receive


def test_allreduce_matches_tpu_collective(world8):
    """Parity: the simulator's Allreduce equals the real device-collective
    allreduce (comm.collectives via shard_map) on the same per-rank data."""
    import jax.numpy as jnp

    n = world8.num_devices
    data = np.arange(n * 3, dtype=np.float32).reshape(n, 3)

    device_result = np.asarray(world8.allreduce(jnp.asarray(data)))[0]

    def main():
        mpiT.Init()
        r = mpiT.Comm_rank(mpiT.COMM_WORLD)
        return mpiT.Allreduce(data[r])

    sim_result = mpiT.run(main, n)[0]
    np.testing.assert_allclose(sim_result, device_result, rtol=1e-6)
