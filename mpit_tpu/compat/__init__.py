"""``mpit_tpu.compat`` — the ``mpiT``-flavored facade.

Reference capability (SURVEY.md §3.1 C1/C3): the ``mpiT`` Lua module —
``Init``/``Initialized``/``Finalize``, ``Comm_rank``/``Comm_size``/
``Get_processor_name``, blocking ``Send``/``Recv``, nonblocking
``Isend``/``Irecv`` with request objects and ``Wait``/``Test``/``Probe``,
collectives ``Barrier``/``Bcast``/``Reduce``/``Allreduce``, and the datatype/
communicator constants (``mpiT.DOUBLE``, ``mpiT.FLOAT``, ``mpiT.INT``,
``mpiT.COMM_WORLD``, ``ANY_SOURCE``, ``ANY_TAG``).

TPU-native position of this module (SURVEY.md §8.2.6, §8.4.1): tagged,
receiver-driven async P2P has **no XLA/SPMD equivalent** — on the TPU the
reference's two-actor protocol collapses into one synchronous jitted step
(see ``mpit_tpu.train.step``). This facade therefore serves two distinct,
honest purposes:

1. **API parity + porting**: reference-shaped scripts (``pserver.lua`` /
   ``pclient.lua`` style rank-role programs) run unchanged in semantics on a
   host-level **multi-rank simulator** (:mod:`mpit_tpu.compat.simulator`):
   each MPI rank is a Python thread, messages flow through tag-matched
   mailboxes, collectives rendezvous at barriers. This is the in-tree
   replacement for "``mpirun -n P`` on localhost *is* the fake cluster"
   (SURVEY.md §5.1) — and it is what the ``asyncsgd`` parity actors and the
   Downpour/EASGD dynamics tests run on.
2. **Semantic documentation**: every entry point's docstring states what the
   operation collapses to on the TPU path, so a reference user migrating a
   script knows exactly where to land in ``mpit_tpu.comm``/``train``.

Usage (the ``mpirun -n 4 th script.lua`` analogue)::

    from mpit_tpu import compat as mpiT

    def main():
        mpiT.Init()
        rank = mpiT.Comm_rank(mpiT.COMM_WORLD)
        size = mpiT.Comm_size(mpiT.COMM_WORLD)
        ...
        mpiT.Finalize()

    mpiT.run(main, nranks=4)
"""

from mpit_tpu.compat.faults import (  # noqa: F401
    FaultPlan,
    MessageRule,
    ReplicaKilled,
    Slowdown,
    StepAction,
)
from mpit_tpu.compat.simulator import (  # noqa: F401
    ANY_SOURCE,
    AbortedError,
    ANY_TAG,
    CompatTimeoutError,
    bind_thread,
    BYTE,
    CHAR,
    COMM_WORLD,
    DOUBLE,
    FLOAT,
    INT,
    LONG,
    MAX,
    MIN,
    PROD,
    SUM,
    Allreduce,
    Barrier,
    Bcast,
    Comm,
    Comm_dup,
    Comm_rank,
    Comm_size,
    Finalize,
    Get_processor_name,
    Init,
    Initialized,
    Irecv,
    Isend,
    Probe,
    Recv,
    Reduce,
    Request,
    Send,
    Status,
    Test,
    Wait,
    Waitall,
    run,
)
