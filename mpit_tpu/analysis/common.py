"""Shared plumbing for the static contract checker (ISSUE 14).

One :class:`Violation` shape for every pass (lint / kernel / jaxpr /
lockdep), one suppression syntax, one in-file directive syntax:

- ``# analysis: allow(<rule>) <reason>`` on a line (or the line above
  it) suppresses that rule's violation at that line. ``allow(*)``
  suppresses every rule. A reason is not enforced but the repo
  convention is to state the invariant that makes the site deliberate
  (the suppression IS documentation — e.g. an engine step wrapper's
  completion fence).
- ``# analysis: hot-seam`` / ``# analysis: determinism-seam`` /
  ``# analysis: pallas-kernel`` — role directives. On (or immediately
  above) a ``def`` line they mark that function; on a bare line they
  mark the whole module. The repo's own seams are named centrally in
  ``lint.DEFAULT_CONFIG`` so package files need no markers; directives
  are the extension mechanism (new modules, the test corpus).

Exit-code contract (the CLI's and the tier-1 test's): 0 = clean,
1 = violations, 2 = unusable (unreadable / unparseable target, bad
invocation) — the same 0/1/2 grammar as ``python -m mpit_tpu.obs diff``.
"""

from __future__ import annotations

import ast
import dataclasses
import re

__all__ = [
    "Violation",
    "SourceFile",
    "RULES",
    "register_rule",
    "qualname_visit",
]

# Registry: rule name -> one-line description (the CLI's --list-rules).
RULES: dict[str, str] = {}


def register_rule(name: str, description: str) -> str:
    RULES[name] = description
    return name


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding: ``path:line: [rule] message``."""

    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


_ALLOW_RE = re.compile(r"#\s*analysis:\s*allow\(([\w*-]+)\)")
_DIRECTIVE_RE = re.compile(r"#\s*analysis:\s*([\w-]+)\s*$")


class SourceFile:
    """A parsed target: source, AST, suppressions and role directives.

    Parsing happens once per file per sweep; every pass shares the
    instance. ``tree`` is ``None`` when the file does not parse —
    callers surface that as the exit-2 "unusable" verdict, never as a
    silent skip.
    """

    def __init__(self, path: str, text: str | None = None):
        self.path = path
        if text is None:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        self.text = text
        self.lines = text.splitlines()
        self.parse_error: str | None = None
        try:
            self.tree: ast.Module | None = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = f"{e.msg} (line {e.lineno})"
        # line -> set of allowed rule names ("*" = all)
        self._allow: dict[int, set[str]] = {}
        # role -> line numbers carrying the directive
        self.directives: dict[str, list[int]] = {}
        for i, line in enumerate(self.lines, start=1):
            for m in _ALLOW_RE.finditer(line):
                self._allow.setdefault(i, set()).add(m.group(1))
            m = _DIRECTIVE_RE.search(line)
            if m and m.group(1) != "allow":
                self.directives.setdefault(m.group(1), []).append(i)

    # -- suppression ------------------------------------------------------

    def suppressed(self, rule: str, line: int) -> bool:
        """A violation at ``line`` is suppressed by an allow() on that
        line or the line directly above it (the comment-above idiom)."""
        for ln in (line, line - 1):
            allowed = self._allow.get(ln)
            if allowed and (rule in allowed or "*" in allowed):
                return True
        return False

    # -- directives -------------------------------------------------------

    def module_role(self, role: str) -> bool:
        """True when the module carries a bare ``# analysis: <role>``
        line at module level (not attached to a def)."""
        for ln in self.directives.get(role, []):
            if not self._def_at_or_below(ln):
                return True
        return False

    def func_role(self, role: str, func_line: int) -> bool:
        """True when the directive sits on the ``def`` line or the line
        directly above it."""
        return any(
            ln in (func_line, func_line - 1)
            for ln in self.directives.get(role, [])
        )

    def _def_at_or_below(self, ln: int) -> bool:
        for probe in (ln, ln + 1):
            if 1 <= probe <= len(self.lines) and re.match(
                r"\s*(async\s+)?def\s", self.lines[probe - 1]
            ):
                return True
        return False

    def violation(self, rule: str, node_or_line, message: str):
        """Build a Violation unless suppressed; returns None when
        suppressed."""
        line = getattr(node_or_line, "lineno", node_or_line)
        if self.suppressed(rule, line):
            return None
        return Violation(rule=rule, path=self.path, line=line, message=message)


def qualname_visit(tree: ast.Module):
    """Yield ``(qualname, FunctionDef)`` for every function in the
    module, with ``Class.method`` / ``outer.<locals>.inner`` spelling
    collapsed to dotted names (``Class.method``, ``outer.inner``)."""
    out: list[tuple[str, ast.AST]] = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((q, child))
                walk(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out
