"""Sharded checkpoint/resume (orbax-backed).

The reference has no checkpoint format — at most ``torch.save`` of the
model in a training script; the PS protocol state (goo state on the server)
is lost on failure (SURVEY.md §6). Here checkpointing is first-class and
sharding-aware: params, the *sharded* goo state, step counter and extra
state are saved asynchronously and restored onto the same (or a compatible)
mesh layout — restore rebuilds each array with the sharding derived from
the trainer's PartitionSpecs.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp
from jax.sharding import NamedSharding


class CheckpointManager:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``.

    ``specs`` (a pytree of PartitionSpecs matching the state, e.g. from
    ``make_train_step``'s ``state_specs``) + the world's mesh determine how
    arrays are laid out on restore.
    """

    def __init__(
        self,
        directory: str | Path,
        world,
        *,
        max_to_keep: int = 3,
        async_save: bool = True,
    ):
        self._world = world
        self._dir = Path(directory).absolute()
        self._pending_meta: dict | None = None
        self._async_save = async_save
        self._meta_flush_on_wait = False
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=async_save
            ),
        )

    def ensure_meta(self, meta: dict, *, defaults: dict | None = None) -> None:
        """Pin run geometry to the checkpoint directory.

        While the directory holds a restorable checkpoint, every run
        against it must present the same ``meta`` values — a resume with,
        say, a different ``warmup_cosine`` horizon silently reshapes the
        LR curve under the restored ``GooState.count``, and a different
        batch size / seed / data source silently diverges the
        fast-forwarded data order; geometry drift is an error, not a
        footnote (RECOVERY.md). With nothing to resume (fresh directory,
        or a run that died before its first save) the guarantee is
        vacuous, so the meta is (re)written instead of validated. Only
        process 0 writes (orbax convention); every process validates.

        ``defaults``: the meta a default-configured run would record
        (runner passes ``run_meta(type(cfg)())``). Used when merging
        fields the recorded meta predates: a newly-added field pinned at
        its default is benign (the original run implicitly ran the
        default), but a NON-default value cannot be validated against
        the original run — it is merged with a warning, like the
        no-meta path (round-4 advisor finding: resuming a pre-
        ``train_size`` checkpoint with ``--train-size 16`` silently
        changed data geometry and recorded 16 as if always so).
        """
        path = self._dir / "run_meta.json"
        if path.exists() and self.latest_step() is not None:
            try:
                with open(path) as f:
                    recorded = json.load(f)
            except (json.JSONDecodeError, OSError) as e:
                raise ValueError(
                    f"{path} is unreadable ({e}); the checkpoint directory "
                    "has a checkpoint but corrupt run metadata — delete "
                    "run_meta.json to re-pin it from this run's flags"
                ) from None
            drift = {
                k: (recorded.get(k), v)
                for k, v in meta.items()
                if k in recorded and recorded[k] != v
            }
            if drift:
                lines = ", ".join(
                    f"{k}: checkpoint has {a!r}, this run has {b!r}"
                    for k, (a, b) in drift.items()
                )
                raise ValueError(
                    f"checkpoint directory {self._dir} was written by a run "
                    f"with different geometry ({lines}); pass matching flags "
                    "(e.g. --schedule-horizon pins the decay length across "
                    "runs with different --steps) or use a fresh --ckpt-dir"
                )
            # Validation passed. Geometry fields this framework version
            # added but the recorded meta predates were skipped above —
            # merge them in (process 0) so subsequent resumes validate the
            # full field set instead of leaving them unvalidated forever
            # (round-3 advisor finding).
            unrecorded = {k: v for k, v in meta.items() if k not in recorded}
            nondefault = {
                k: v
                for k, v in unrecorded.items()
                if defaults is not None and v != defaults.get(k)
            }
            if nondefault:
                import warnings

                fields = ", ".join(
                    f"{k}={v!r}" for k, v in sorted(nondefault.items())
                )
                warnings.warn(
                    f"{self._dir} predates geometry field(s) {fields}; "
                    "pinning this run's non-default value(s) — drift "
                    "against the run that wrote the checkpoint (which "
                    "implicitly ran the old default) cannot be validated",
                    stacklevel=2,
                )
            if unrecorded:
                # Deferred merge (round-5 advisor finding): do NOT write
                # the widened meta yet. Pinning here — before the restore
                # has succeeded — records this run's values for fields
                # the original run never declared, so a failed/aborted
                # resume (e.g. a pre-round-5 flax-BN checkpoint first
                # retried with the wrong --bn-impl) poisons run_meta.json
                # and the *corrected* retry then fails validation against
                # geometry that was only ever attempted. The merge lands
                # after the first successful restore() (or first save(),
                # for callers that validate without restoring).
                self._pending_meta = {**recorded, **unrecorded}
            return
        if not path.exists() and self.latest_step() is not None:
            # Pre-upgrade directory (checkpoint written before run-meta
            # pinning existed, or the user deleted a corrupt meta): the
            # original geometry is unknowable, so pin this run's flags —
            # but say so, since drift against the ORIGINAL run cannot be
            # detected.
            import warnings

            warnings.warn(
                f"{self._dir} holds a checkpoint but no run_meta.json; "
                "pinning this run's flags as the geometry — drift against "
                "the run that wrote the checkpoint cannot be validated",
                stacklevel=2,
            )
        if jax.process_index() == 0:
            self._dir.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(".json.tmp")
            with open(tmp, "w") as f:
                json.dump(meta, f, indent=1)
            os.replace(tmp, path)  # atomic: no partial file is ever visible

    def _flush_pending_meta(self) -> None:
        """Write the deferred ensure_meta merge (see its docstring): the
        run has now demonstrably worked against this directory, so the
        widened geometry can be pinned. Process 0 writes; atomic."""
        merged, self._pending_meta = self._pending_meta, None
        if merged is None or jax.process_index() != 0:
            return
        path = self._dir / "run_meta.json"
        tmp = path.with_suffix(".json.tmp")
        with open(tmp, "w") as f:
            json.dump(merged, f, indent=1)
        os.replace(tmp, path)

    def save(self, step: int, state: Any) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        # AFTER the save is accepted: a first save that raises must not
        # pin attempted-only geometry (same rule as restore()). An ASYNC
        # save has only been staged here — its background write can still
        # fail (disk full, preemption), surfacing at wait() — so the
        # flush waits for durability before pinning; a synchronous save
        # is already durable (round-6 review finding).
        if self._async_save:
            self._meta_flush_on_wait = True
        else:
            self._flush_pending_meta()

    def restore(self, state_like: Any, specs: Any, *, step: int | None = None):
        """Restore the checkpoint at ``step`` (default: latest).

        ``state_like`` supplies shapes/dtypes (concrete or abstract arrays);
        ``specs`` the layout — PartitionSpecs (the shard_map tiers'
        ``state_specs``) or ready-made ``NamedSharding``s (the pjit tier's
        ``shardings_fn``).
        """
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint found")
        mesh = self._world.mesh

        def as_sharding(s):
            return s if isinstance(s, NamedSharding) else NamedSharding(mesh, s)

        abstract = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=as_sharding(s)
            ),
            state_like,
            specs,
        )
        out = self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))
        # Restore succeeded: safe to pin any geometry fields ensure_meta
        # deferred (a failed restore must leave run_meta.json untouched).
        self._flush_pending_meta()
        return out

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        self._mgr.wait_until_finished()
        if getattr(self, "_meta_flush_on_wait", False):
            # The staged save(s) are now durable: the deferred
            # ensure_meta merge may pin (see save()).
            self._meta_flush_on_wait = False
            self._flush_pending_meta()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        self.close()


class AtomicCheckpoint:
    """Crash-consistent host-level pytree checkpoints (ISSUE 11).

    The elastic tier's per-replica checkpoints: each replica's small
    flat-param state saves as one ``.npz`` written to a temp file and
    published with ``os.replace`` — a replica killed at ANY byte of the
    write can never leave a torn checkpoint where rejoin would restore
    it. A visible ``step_*.npz`` is by construction complete; temp files
    (``.tmp-*``) are never scanned and a fresh save at the same step
    simply replaces them.

    Duck-types the :class:`CheckpointManager` surface ``hardened_loop``
    needs (``save``/``restore``/``latest_step``/``all_steps``/``wait``),
    so the production loop's divergence-restore/older-checkpoint-backoff
    machinery drives it unchanged; ``specs`` is accepted and ignored
    (host-level state has no device layout). ``restore`` rebuilds the
    pytree from ``state_like``'s treedef, so any fixed-structure state
    (e.g. ``TrainState(step, flat_params, opt_state)``) round-trips.
    Saves are synchronous (``wait`` is a no-op) — the payloads are
    host-sized flat vectors, not sharded HBM tensors.
    """

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3):
        self._dir = Path(directory).absolute()
        self._dir.mkdir(parents=True, exist_ok=True)
        self._max_to_keep = max_to_keep

    def _path(self, step: int) -> Path:
        return self._dir / f"step_{step:010d}.npz"

    def save(self, step: int, state: Any) -> None:
        import numpy as np

        leaves, _ = jax.tree.flatten(state)
        arrays = {f"leaf_{i:04d}": np.asarray(l) for i, l in enumerate(leaves)}
        tmp = self._dir / f".tmp-step_{step:010d}-{os.getpid()}.npz"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                os.fsync(f.fileno())
        except BaseException:
            # A failed/interrupted write must leave no debris a future
            # save at this step would trip on; the PUBLISHED files are
            # untouched either way (that is the whole point).
            tmp.unlink(missing_ok=True)
            raise
        os.replace(tmp, self._path(step))  # atomic publish
        self._prune()

    def _prune(self) -> None:
        steps = self.all_steps()
        for s in steps[: max(0, len(steps) - self._max_to_keep)]:
            self._path(s).unlink(missing_ok=True)

    def restore(self, state_like: Any, specs: Any = None, *, step: int | None = None):
        """Rebuild ``state_like``'s pytree from the checkpoint at
        ``step`` (default latest). ``specs`` ignored (host-level)."""
        del specs
        import numpy as np

        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {self._dir}")
        leaves_like, treedef = jax.tree.flatten(state_like)
        with np.load(self._path(step)) as z:
            # Numeric sort: a lexicographic one would misorder leaf
            # names past the zero-pad width (leaf_10000 < leaf_2000).
            names = sorted(z.files, key=lambda n: int(n.rsplit("_", 1)[1]))
            if len(names) != len(leaves_like):
                raise ValueError(
                    f"checkpoint at step {step} has {len(names)} leaves, "
                    f"state_like has {len(leaves_like)} — structure drift"
                )
            leaves = [z[n] for n in names]
        return jax.tree.unflatten(treedef, leaves)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def all_steps(self) -> list[int]:
        out = []
        for p in self._dir.glob("step_*.npz"):
            try:
                out.append(int(p.stem.split("_", 1)[1]))
            except ValueError:
                continue  # foreign file; not ours to interpret
        return sorted(out)

    def wait(self) -> None:
        pass  # synchronous saves: already durable

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass
