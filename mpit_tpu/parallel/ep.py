"""Expert-parallel training tier: GPT-2-MoE over a ``data x expert`` mesh.

Round-1 shipped the MoE dispatch as a tested library shelf; this module
is the usable strategy the verdict asked for (item 6): a full jitted
training step where

- tokens are sharded over BOTH axes (batch dim split across every
  device — expert parallelism subdivides the data-parallel group, the
  GShard layout);
- expert weights live sharded over ``expert`` (each device owns
  ``E / n_expert`` experts, replicated over ``data``); the dispatch
  all-to-alls inside :func:`~mpit_tpu.parallel.moe.expert_parallel_moe`
  route token slots to their expert's owner and back;
- the objective is globally normalized (local token-loss sum divided by
  the global token count, plus ``aux_weight`` times the local
  load-balance aux divided by the device count), so every gradient
  completes by plain SUM: expert grads arrive complete per shard (the
  all-to-all transpose collects the whole expert group's cotangents) and
  psum over ``data``; non-expert grads auto-psum over ``expert``
  (unvaried — the round-2 vary doctrine, ``parallel.threed``) and psum
  over ``data``;
- ZeRO-1 shards goo state over ``data`` per placement group (expert
  leaves / everything else) with sum semantics.

Semantics note: the load-balance aux is computed PER DEVICE over its
local tokens and then averaged — the standard per-group Switch
formulation. Because the aux is nonlinear in its token statistics
(E·Σ f_e·p_e of per-token means), this differs from an aux computed over
the global batch by Jensen-gap terms; the xent part of the objective is
exactly the global mean (dense-parity-tested with ``aux_weight=0``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from mpit_tpu import opt as gopt
from mpit_tpu.comm import collectives as C
from mpit_tpu.models.gpt2 import GPT2Config

# NOTE: models.gpt2_moe imports parallel.moe, so importing it at module
# scope from inside the parallel package would be circular — the model
# symbols are imported lazily in make_gpt2_moe_train_step.
from mpit_tpu.opt.sharded import grouped_state_specs
from mpit_tpu.train.step import TrainState

import dataclasses


def _moe_model():
    from mpit_tpu.models import gpt2_moe

    return gpt2_moe


def _is_expert_leaf(path) -> bool:
    # Delegate to the model's own definition (lazy for the circular-import
    # reason above) so the expert-leaf name set lives in exactly one place.
    return _moe_model().is_expert_leaf(path)


def _partition_expert_tree(tree):
    """(expert-leaves, other-leaves) as complementary None-hole trees."""

    def pick(want):
        def f(path, leaf):
            return leaf if _is_expert_leaf(path) == want else None

        return jax.tree_util.tree_map_with_path(f, tree)

    return pick(True), pick(False)


from mpit_tpu.parallel.threed import _merge  # shared hole-tree overlay


def make_gpt2_moe_train_step(
    cfg: GPT2Config,
    moe,
    tx: optax.GradientTransformation,
    world,
    *,
    data_axis: str = "data",
    expert_axis: str = "expert",
    aux_weight: float = 0.01,
    zero1: bool = True,
    donate: bool = True,
):
    """Build ``(init_fn, step_fn, state_specs)`` for expert-parallel
    GPT-2-MoE. Batch ``{"tokens": [B_global, T+1]}`` sharded
    ``P((data_axis, expert_axis))`` on the batch dim; params from
    ``GPT2MoE(cfg, moe).init`` (dense layout — in_specs shard the expert
    leaves). Requires ``moe.num_experts % n_expert == 0``.
    """
    gm = _moe_model()
    n_expert = world.axis_size(expert_axis)
    n_data = world.axis_size(data_axis)
    if moe.num_experts % n_expert:
        raise ValueError(
            f"num_experts ({moe.num_experts}) must divide by the expert "
            f"axis ({n_expert})"
        )
    model = gm.GPT2MoE(
        cfg,
        dataclasses.replace(
            moe, axis_name=expert_axis, reduce_aux=False, shards=n_expert
        ),
    )
    n_total = n_data * n_expert

    def _specs(params):
        return gm.expert_param_specs(params, expert_axis)

    def _opt_specs(params):
        g_exp, g_rest = _partition_expert_tree(params)
        if not zero1:
            shapes = jax.eval_shape(tx.init, params)

            def spec_for(path, leaf):
                if getattr(leaf, "ndim", 0) == 0:
                    return P()
                return (
                    P(expert_axis) if _is_expert_leaf(path) else P()
                )

            return jax.tree_util.tree_map_with_path(spec_for, shapes)

        return {
            "expert": grouped_state_specs(
                tx, g_exp, n_data, data_axis, (expert_axis, data_axis)
            ),
            "rest": grouped_state_specs(
                tx, g_rest, n_data, data_axis, (data_axis,)
            ),
        }

    def state_specs(params, extra=()):
        del extra
        return TrainState(
            step=P(),
            params=_specs(params),
            opt_state=_opt_specs(params),
            extra=(),
        )

    def _per_device_init(params):
        if zero1:
            g_exp, g_rest = _partition_expert_tree(params)
            stx = gopt.sharded(tx, data_axis)
            opt_state = {"expert": stx.init(g_exp), "rest": stx.init(g_rest)}
        else:
            opt_state = tx.init(params)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            extra=(),
        )

    def init_fn(params, extra=()) -> TrainState:
        del extra
        f = world.shard_map(
            _per_device_init,
            in_specs=(_specs(params),),
            out_specs=state_specs(params),
        )
        return jax.jit(f)(params)

    def _per_device_step(state: TrainState, batch):
        tokens = batch["tokens"]  # [b_local, T+1]
        inp, targets = tokens[:, :-1], tokens[:, 1:]
        local_tokens = inp.shape[0] * inp.shape[1]
        global_tokens = local_tokens * n_total

        # Vary doctrine: expert leaves genuinely differ per expert
        # coordinate → vary over (data, expert); everything else varies
        # over data only, so AD auto-psums its cotangents over expert.
        def vary_leaf(path, leaf):
            axes = (
                (data_axis, expert_axis)
                if _is_expert_leaf(path)
                else (data_axis,)
            )
            return C.vary(leaf, axes)

        local = jax.tree_util.tree_map_with_path(vary_leaf, state.params)

        def loss_fn(p):
            losses, aux = model.apply({"params": p}, inp, targets=targets)
            # Global-mean xent + global-mean aux, in SUM semantics: every
            # device contributes its local share over global counts.
            return (
                jnp.sum(losses) / global_tokens
                + aux_weight * aux / n_total,
                (jnp.sum(losses) / global_tokens, aux / n_total),
            )

        (_, (xent_share, aux_share)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(local)

        if zero1:
            g_exp, g_rest = _partition_expert_tree(grads)
            p_exp, p_rest = _partition_expert_tree(state.params)
            stx = gopt.sharded(tx, data_axis, mean_grads=False)
            u_exp, st_exp = stx.update(
                g_exp, state.opt_state["expert"], p_exp
            )
            u_rest, st_rest = stx.update(
                g_rest, state.opt_state["rest"], p_rest
            )
            updates = _merge(u_exp, u_rest)
            opt_state = {"expert": st_exp, "rest": st_rest}
        else:
            grads = jax.tree.map(lambda g: lax.psum(g, data_axis), grads)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)

        metrics = {
            "loss": lax.psum(
                lax.psum(xent_share, expert_axis), data_axis
            ),
            "aux": lax.psum(lax.psum(aux_share, expert_axis), data_axis),
        }
        return (
            TrainState(
                step=state.step + 1, params=params, opt_state=opt_state,
                extra=(),
            ),
            metrics,
        )

    compiled: dict = {}

    def build(params):
        specs = state_specs(params)
        return jax.jit(
            world.shard_map(
                _per_device_step,
                in_specs=(specs, P((data_axis, expert_axis))),
                out_specs=(specs, P()),
            ),
            donate_argnums=(0,) if donate else (),
        )

    def step_fn(state: TrainState, batch):
        key = jax.tree_util.tree_structure(state.params)
        f = compiled.get(key)
        if f is None:
            f = build(state.params)
            compiled[key] = f
        return f(state, batch)

    # AOT seam for utils/aot.py compile_multichip.
    step_fn.build = build
    return init_fn, step_fn, state_specs
