"""Corpus false-positive guards for thread-bind: a bound helper thread
(the elastic heartbeat idiom), and a thread that never touches compat
(the prefetch-worker idiom)."""

import threading


def start_heartbeat(rank, comm, mpiT, np):
    def _beat():
        mpiT.bind_thread(rank, comm)
        mpiT.Send(np.asarray([rank]), dest=0, tag=7, comm=comm)

    threading.Thread(target=_beat, daemon=True).start()  # bound: fine


def start_prefetch(queue, fetch):
    def _work():
        queue.put(fetch())

    threading.Thread(target=_work, daemon=True).start()  # no compat: fine
