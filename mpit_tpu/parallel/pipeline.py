"""Pipeline parallelism: GPipe microbatch ring over a ``pipe`` mesh axis.

Absent from the reference (SURVEY.md §3.3). TPU-native design: the P stages
are the P devices along axis ``pipe``; activations move stage→stage with
``lax.ppermute`` (one ICI neighbor hop) inside a single ``lax.scan`` of
``M + P - 1`` ticks (M microbatches + P-1 bubble ticks). The whole schedule
is one jitted SPMD program — no host round-trips between ticks — and is
differentiable end-to-end: AD of ``ppermute`` is the reverse permute, so
the backward pass is automatically the reverse pipeline with its own
bubble.

Layout: stage s's parameters live only on device s (in practice: stack the
per-stage parameter trees on a leading [P, ...] axis and pass them through
``shard_map`` with ``in_specs=P('pipe')``, so each device receives its
[1, ...] slice). Every device sees the full [M, ...] microbatch array; only
stage 0 reads it, only stage P-1's outputs are real, and the result is
broadcast so it exits ``shard_map`` replicated.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from mpit_tpu.comm import collectives as C


def spmd_pipeline(
    stage_fn: Callable,
    stage_params,
    microbatches,
    *,
    axis: str = "pipe",
):
    """Run ``microbatches`` through P pipeline stages; call inside shard_map.

    Args:
      stage_fn: ``stage_fn(stage_params, x) -> y`` — this device's stage.
        Activation shape must be stage-invariant (y.shape == x.shape), the
        usual transformer-block case; project in/out outside the pipeline.
      stage_params: the LOCAL stage's params. If the leaves carry the
        stacked leading axis (shard_map in_specs ``P('pipe')`` leaves a
        leading dim of 1), it is squeezed automatically.
      microbatches: [M, ...] — the batch pre-split into M microbatches,
        replicated across the axis.

    Returns [M, ...] outputs, replicated (broadcast from the last stage).
    """
    n = lax.axis_size(axis)
    i = lax.axis_index(axis)
    m = microbatches.shape[0]

    def maybe_squeeze(leaf):
        return leaf[0] if leaf.ndim >= 1 and leaf.shape[0] == 1 else leaf

    params = jax.tree.map(maybe_squeeze, stage_params)

    # Initial carry must be typed device-varying for shard_map's VMA checker
    # (each stage's state/outputs genuinely differ per device).
    state, outputs = C.vary(
        (jnp.zeros_like(microbatches[0]), jnp.zeros_like(microbatches)), axis
    )

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 ingests microbatch t (clamped during the drain bubble —
        # those ticks' outputs never land anywhere); later stages consume
        # what arrived from the previous stage last tick.
        feed = microbatches[jnp.minimum(t, m - 1)]
        x = jnp.where(i == 0, feed, state)
        y = stage_fn(params, x)
        # Last stage owns microbatch t-(P-1) once the pipe is full.
        out_idx = jnp.clip(t - (n - 1), 0, m - 1)
        landed = jnp.where(
            (i == n - 1) & (t >= n - 1), y, outputs[out_idx]
        )
        outputs = lax.dynamic_update_index_in_dim(outputs, landed, out_idx, 0)
        # One ring hop: stage i → i+1 (the wrap edge P-1 → 0 is ignored by
        # stage 0, which reads from the feed).
        state = C.shift(y, axis, offset=1)
        return (state, outputs), None

    (_, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(m + n - 1)
    )
    # Only the last stage holds real outputs; replicate them.
    return C.broadcast(outputs, axis, root=n - 1)


def stack_stage_params(per_stage_params: list):
    """Stack per-stage param trees on a new leading [P, ...] axis — the
    layout :func:`spmd_pipeline` expects via in_specs ``P('pipe')``."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
