"""mpit_tpu.analysis — the repo-native static contract checker (ISSUE 14).

Thirteen PRs of informal invariants — "no ``[slots, vocab]`` logits in
the decode jaxpr", "every async copy started is waited", "restage
before the capacity token releases", "utilization percentages only on
TPU", "pinned seams consume no wall clock" — enforced mechanically
across the whole package, the way a sanitizer would be in a C++ stack.

Four passes, one CLI, one exit-code grammar (0 clean / 1 violations /
2 unusable):

- :mod:`.lint` — AST rules over the package's host code (hot-seam
  host-sync, per-tick jit, determinism seams, utilization gates,
  thread binding).
- :mod:`.jaxpr_check` — the reusable jaxpr-contract library (the
  serving tests' aval greps, audited and shared) + a sweep tracing
  every registered jitted step against its declared contracts.
- :mod:`.kernel_check` — the Pallas kernel verifier: DMA-semaphore
  balance, the ``_Ring`` restage-before-release ordering, planner tile
  math + VMEM pins, and the exhaustive ``_Ring`` protocol model check
  (P ∈ {2,3,4}).
- :mod:`.lockdep` — the runtime lock-order auditor (a pytest hook
  keeps it on for the threaded suites; cycles fail loudly, named).

CLI::

    python -m mpit_tpu.analysis [paths...] [--rule R]... [--changed]
    python -m mpit_tpu.analysis --list-rules

``--changed`` scopes the sweep to files touched per ``git status`` —
the pre-commit entry point. The full-package run is a tier-1 test
(``tests/test_analysis.py``), so every future PR is checked against
every invariant, not just the ones its author remembered.
"""

from __future__ import annotations

from mpit_tpu.analysis.common import RULES, SourceFile, Violation

__all__ = [
    "RULES", "SourceFile", "Violation", "run", "collect_files",
    "ChangedScopeError",
]


class ChangedScopeError(RuntimeError):
    """--changed could not resolve the git change set (no repo / no
    git): the analyzer cannot analyze, so it must NOT report clean —
    surfaced as the exit-2 unusable verdict, never as an empty scope."""


def _git_changed_set(anchor: str) -> set:
    """Absolute real paths of every modified/untracked ``.py`` file in
    the repository that owns ``anchor`` (a target path — NOT the
    process cwd: a cwd in a different repo would intersect the wrong
    change set and report silently 'clean'; review finding). Git names
    are repo-root-relative, so they are re-anchored at the toplevel —
    target paths may be absolute or cwd-relative and still intersect
    correctly."""
    import os
    import subprocess

    try:
        top = subprocess.run(
            ["git", "-C", anchor, "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        # -uall: plain porcelain collapses an untracked DIRECTORY to
        # one "?? dir/" entry, which would silently drop every .py
        # file inside a brand-new module from the pre-commit scope
        # (review finding, reproduced on this very repo).
        out = subprocess.run(
            ["git", "-C", anchor, "status", "--porcelain",
             "--untracked-files=all"],
            capture_output=True, text=True, check=True,
        ).stdout
    except Exception as e:
        raise ChangedScopeError(
            f"--changed could not read the git change set: {e}"
        ) from e
    touched = set()
    for line in out.splitlines():
        name = line[3:].strip()
        if " -> " in name:
            name = name.split(" -> ")[-1]
        if name.startswith('"') and name.endswith('"'):
            # Porcelain C-quotes paths with spaces/escapes/non-ASCII;
            # left quoted, such a file silently drops out of the
            # pre-commit scope (review finding — the same silent-clean
            # class as the -uall fix above).
            name = (
                name[1:-1]
                .encode("utf-8")
                .decode("unicode_escape")
                .encode("latin-1")
                .decode("utf-8", errors="replace")
            )
        if name.endswith(".py"):
            touched.add(os.path.realpath(os.path.join(top, name)))
    return touched


def collect_files(paths, changed: bool = False) -> tuple[list, list]:
    """Resolve target ``.py`` files. Returns ``(files, missing)``;
    ``changed=True`` intersects with git's modified/untracked set
    (raising :class:`ChangedScopeError` when git cannot answer)."""
    import os

    files: list[str] = []
    missing: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                for n in sorted(names):
                    if n.endswith(".py"):
                        files.append(os.path.join(root, n))
        elif os.path.isfile(p):
            files.append(p)
        else:
            missing.append(p)
    if changed:
        anchor = "."
        for p in paths:
            if os.path.isdir(p):
                anchor = p
                break
            if os.path.isfile(p):
                anchor = os.path.dirname(p) or "."
                break
        touched = _git_changed_set(anchor)
        files = [f for f in files if os.path.realpath(f) in touched]
    # De-dup, stable order.
    seen = set()
    uniq = []
    for f in files:
        key = os.path.normpath(f)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq, missing


def run(
    paths=("mpit_tpu",),
    rules: set | None = None,
    changed: bool = False,
    jaxpr_sweep: bool = True,
    lint_config=None,
) -> tuple[int, list]:
    """Run the static passes; returns ``(exit_code, violations)``.

    ``jaxpr_sweep`` / the kernel dynamic pins import jax and the ops
    modules (tracing only); they run once per invocation when any
    package path is in scope, and are skipped entirely in ``--changed``
    mode with an empty change set.
    """
    import os

    from mpit_tpu.analysis import kernel_check, lint
    from mpit_tpu.analysis.common import Violation as V

    try:
        files, missing = collect_files(paths, changed=changed)
    except ChangedScopeError as e:
        # No git answer ⇒ unusable (exit 2), never a silent "clean".
        return 2, [V("analysis", "--changed", 0, str(e))]
    violations: list = []
    unusable = False
    for m in missing:
        unusable = True
        violations.append(V("analysis", m, 0, "path does not exist"))
    cfg = lint_config if lint_config is not None else lint.DEFAULT_CONFIG

    any_kernel_file = False
    for path in files:
        try:
            sf = SourceFile(path)
        except (OSError, UnicodeDecodeError, ValueError) as e:
            # Unreadable OR undecodable (a PEP-263 non-UTF8 source is
            # legal Python the reader can't decode) ⇒ exit-2 unusable,
            # never a traceback miscoded as a findings exit (review
            # finding).
            unusable = True
            violations.append(V("analysis", path, 0, f"unreadable: {e}"))
            continue
        if sf.tree is None:
            unusable = True
            violations.append(
                V("analysis", path, 0, f"syntax error: {sf.parse_error}")
            )
            continue
        violations.extend(lint.lint_file(sf, cfg, rules))
        norm = path.replace("\\", "/")
        if any(norm.endswith(k) for k in kernel_check.KERNEL_FILES) or (
            sf.directives.get("pallas-kernel")
        ):
            any_kernel_file = True
            if rules is None or rules & {
                kernel_check.R_DMA, kernel_check.R_RING_ORDER
            }:
                violations.extend(kernel_check.check_kernels_ast(sf))

    if files:
        want_dynamic = rules is None or rules & {
            kernel_check.R_GEOMETRY, kernel_check.R_MODEL
        }
        if want_dynamic and any_kernel_file:
            violations.extend(kernel_check.check_kernels_dynamic(rules))
        from mpit_tpu.analysis.jaxpr_check import R_JAXPR, sweep

        # The traced-contract sweep runs on a full-package invocation,
        # and in --changed mode only when a contract-bearing layer was
        # actually touched (serve/ops/train/models) — the pre-commit
        # path stays fast on doc/host-only edits. Package membership is
        # resolved against the REAL package directory, not a path
        # substring (review finding: a clone under a parent dir named
        # "mpit_tpu" ran the sweep for every single-file invocation).
        pkg_root = os.path.dirname(os.path.dirname(os.path.realpath(__file__)))

        def _pkg_rel(f):
            rf = os.path.realpath(f)
            if rf.startswith(pkg_root + os.sep):
                return rf[len(pkg_root) + 1:].replace(os.sep, "/")
            return None

        rels = [r for r in map(_pkg_rel, files) if r is not None]
        touched_contract = any(
            r.startswith(("serve/", "ops/", "train/", "models/"))
            for r in rels
        )
        if (
            jaxpr_sweep
            and (rules is None or R_JAXPR in rules)
            and ((not changed and rels) or touched_contract)
        ):
            violations.extend(sweep())

    if rules is not None:
        # Global --rule guarantee: no pass may leak a non-selected
        # rule's findings (check_kernels_ast emits both kernel AST
        # rules; lint filters itself — this is the one enforcement
        # point). The synthetic "analysis" unusable markers always
        # survive.
        violations = [
            v for v in violations if v.rule in rules or v.rule == "analysis"
        ]
    if unusable:
        return 2, violations
    return (1 if violations else 0), violations
