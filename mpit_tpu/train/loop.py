"""The training loop: steps, metrics, checkpoints, eval.

The reference's loop is the per-worker ``for each minibatch`` in its
``asyncsgd/`` scripts plus the server's message loop (SURVEY.md §4.2); here
a single :class:`Trainer` drives the jitted SPMD step over a prefetched
sharded data stream.

:func:`hardened_loop` is the production drive loop shared by every
execution path (``runner.run_spmd`` and the gpt2 parallel tiers): one
implementation of prefetch, SIGTERM preemption drain, divergence
guard + older-checkpoint backoff, the profile trace window, periodic
eval, and checkpoint cadence — so the recovery story (RECOVERY.md)
applies to the longest-lived runs (the 3-D/EP tiers on pods), not just
the DP path (round-2 verdict item 4).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterator

import jax

from mpit_tpu import obs
from mpit_tpu.data.loader import Prefetcher
from mpit_tpu.train.guard import Diverged, DivergenceGuard
from mpit_tpu.train.metrics import MetricLogger, Throughput
from mpit_tpu.train.step import TrainState


def hardened_loop(
    world,
    state: Any,
    step_fn: Callable,
    batches: Iterator,
    *,
    steps: int,
    transform: Callable | None = None,
    axis: str = "data",
    items_per_batch: int | None = None,
    log_every: int = 50,
    logger: MetricLogger | None = None,
    ckpt=None,
    ckpt_every: int = 0,
    specs: Callable | None = None,
    max_restores: int = 1,
    spike_factor: float = 0.0,
    profile_dir: str = "",
    final_save: bool = False,
    eval_every: int = 0,
    eval_hook: Callable | None = None,
    dispatch_fence: int = 32,
) -> dict:
    """Drive ``step_fn`` from ``state`` to ``steps`` with full hardening.

    Args:
      state: initial (possibly checkpoint-restored) state; ``state.step``
        is the authoritative resume point.
      step_fn: jitted ``(state, device_batch) -> (state, metrics)``;
        ``metrics`` must contain ``"loss"``.
      batches: host-side batch iterator, already fast-forwarded past
        ``int(state.step)`` consumed batches (seek-based resume is the
        caller's job — it owns the dataset).
      transform: host batch → device batch (slicing + ``shard_batch``
        with the tier's PartitionSpecs). Default: shard the leading dim
        over ``axis``. Runs on the prefetch thread, overlapping compute.
      ckpt / ckpt_every / specs: CheckpointManager, save cadence, and a
        zero-arg callable returning the state's PartitionSpecs (needed
        for divergence restore).
      max_restores / spike_factor: divergence policy (train/guard.py) —
        non-finite or spiking loss restores the newest checkpoint OLDER
        than the previous restore target, up to ``max_restores`` times.
      profile_dir: capture a ``jax.profiler`` trace of steps 2..5 of
        this run (clamped into range).
      final_save: checkpoint at the natural end of the run too (the
        tier paths' contract; run_spmd relies on cadence only).
      eval_every / eval_hook: every N steps (and at the last step) call
        ``eval_hook(state) -> dict`` and log it under ``eval_*`` keys —
        the periodic full-val-split sweep hangs off this.
      dispatch_fence: host-fetch the loss at least every N steps even
        between log points, bounding async-dispatch depth. Two reasons:
        the fake-CPU-mesh backend's in-process collectives starve their
        rendezvous when ~60 collective programs are enqueued unfetched
        ("Expected 8 threads to join" aborts — observed at 1 host core),
        and an unbounded host-ahead window makes preemption drain and
        divergence detection arbitrarily stale. Cost on the tunneled TPU:
        one ~12 ms fetch per N steps — noise at N=32.

    Returns ``{"state", "losses", "restores", "preempted", "steps",
    "eval"}`` (``eval``: the last eval_hook result, or absent).
    """
    if ckpt is not None and specs is None:
        # Fail at configuration time, not deep in the divergence-restore
        # path with an opaque `'NoneType' object is not callable` (round-3
        # advisor finding): restore needs the state's PartitionSpecs.
        raise ValueError(
            "hardened_loop: `ckpt` given without `specs` — divergence "
            "restore re-shards the checkpoint and needs a zero-arg "
            "callable returning the state's PartitionSpecs"
        )
    logger = logger or MetricLogger()
    start_step = int(state.step)
    items = items_per_batch
    log_t: float | None = None  # wall clock at the last forced log fetch
    log_step = start_step

    prof_window = None
    if profile_dir and steps > start_step:
        last = steps - 1
        prof_window = (min(start_step + 2, last), min(start_step + 5, last))

    # Failure detection (SURVEY.md §6): a non-finite/spiking loss at a
    # checked step triggers a restore (when checkpoints exist) and the run
    # continues — up to max_restores times. Checks run at BOTH log and
    # save points, so a checkpoint is never written on a failing loss.
    # (Residual window: loss at step t certifies the params *entering* t,
    # so the state saved at t could in principle already be poisoned while
    # loss_t is finite — which is why repeat divergence steps back to an
    # OLDER checkpoint instead of reloading the same one.) After a restore
    # the stream keeps its position: an interrupted data order is part of
    # divergence recovery; exact replay is only for clean resume.
    guard_ = DivergenceGuard(spike_factor=spike_factor)
    restores = 0
    restore_before: int | None = None  # ceiling for the next restore target

    # Preemption drain (SURVEY.md §6 recovery row; RECOVERY.md): pod
    # maintenance/eviction delivers SIGTERM with a grace window. Catch it,
    # finish the in-flight step, write a final checkpoint, and exit
    # cleanly so the rescheduled job resumes from it.
    preempted = {"flag": False}

    def _on_term(signum, frame):
        del signum, frame
        preempted["flag"] = True

    prev_handler = None
    handler_installed = False
    try:
        import signal

        prev_handler = signal.signal(signal.SIGTERM, _on_term)
        handler_installed = True
    except ValueError:
        pass  # not the main thread (tests, embedded use): no handler

    loss_trace: list[tuple[int, float]] = []
    rate_trace: list[float] = []
    last_eval: dict | None = None
    tracing = False
    trace_done = False
    step = start_step
    try:
        with Prefetcher(world, batches, axis=axis, transform=transform) as stream:
            while True:
                # Telemetry (mpit_tpu.obs, no-op unless obs.enable()d):
                # the loop's phases are spanned so a Chrome-trace export
                # shows where each step's wall clock went — prefetch
                # wait vs dispatch vs host fence vs eval/checkpoint.
                with obs.span("prefetch_wait"):
                    try:
                        batch = next(stream)
                    except StopIteration:
                        break
                if step >= steps:
                    break
                if preempted["flag"]:
                    if ckpt:
                        with obs.span("checkpoint_save", reason="preempted"):
                            if ckpt.latest_step() != step:  # cadence saved it
                                ckpt.save(step, state)
                            ckpt.wait()
                    logger.log(
                        step,
                        {"event": "preempted_checkpoint_and_exit",
                         "resumable": bool(ckpt)},
                    )
                    break
                if (
                    prof_window
                    and not tracing
                    and not trace_done
                    and step == prof_window[0]
                ):
                    jax.profiler.start_trace(profile_dir)
                    tracing = True
                with obs.span("step"):
                    state, metrics = step_fn(state, batch)
                if tracing and step >= prof_window[1]:
                    with obs.span("host_fence", why="trace_window"):
                        float(metrics["loss"])  # host fetch: trace covers real work
                    jax.profiler.stop_trace()
                    tracing = False
                    trace_done = True
                should_log = (step + 1) % log_every == 0 or step + 1 == steps
                should_save = bool(
                    ckpt and ckpt_every and (step + 1) % ckpt_every == 0
                )
                should_eval = bool(
                    eval_hook
                    and eval_every
                    and ((step + 1) % eval_every == 0 or step + 1 == steps)
                )
                if not (should_log or should_save) and (
                    dispatch_fence and (step + 1) % dispatch_fence == 0
                ):
                    with obs.span("host_fence", why="dispatch_fence"):
                        float(metrics["loss"])  # bound async-dispatch depth
                if should_log or should_save:
                    with obs.span("host_fence", why="log"):
                        loss = float(metrics["loss"])
                    try:
                        guard_.check(step + 1, loss)
                    except Diverged:
                        candidates = [
                            s
                            for s in (ckpt.all_steps() if ckpt else [])
                            if restore_before is None or s < restore_before
                        ]
                        if not candidates or restores >= max_restores:
                            raise
                        target = max(candidates)
                        restores += 1
                        if tracing:
                            # The step counter jumps backward across the
                            # restore; a window left open would silently
                            # span the rollback discontinuity (round-3
                            # advisor finding). End the capture here.
                            jax.profiler.stop_trace()
                            tracing = False
                            trace_done = True
                        with obs.span("divergence_restore", target=target):
                            state = ckpt.restore(state, specs(), step=target)
                        step = int(state.step)
                        restore_before = target
                        guard_.reset()
                        loss_trace = [(s, l) for s, l in loss_trace if s <= step]
                        # Throughput bookkeeping must not straddle the
                        # rollback: the step counter just jumped backward,
                        # so a live log window would compute a NEGATIVE
                        # items_per_sec for the first post-restore log
                        # (round-5 advisor finding). Start a fresh window.
                        log_t, log_step = None, step
                        logger.log(
                            step,
                            {"event": "restored_after_divergence",
                             "bad_loss": loss, "restores": restores},
                        )
                        continue
                    if should_log:
                        loss_trace.append((step + 1, loss))
                        out = {k: float(v) for k, v in metrics.items()}
                        # Interval throughput, measured BETWEEN forced
                        # host fetches: the float(loss) above drained the
                        # async dispatch queue, so the interval's wall
                        # clock covers real device execution. (A per-step
                        # tick would time the host DISPATCH of steps the
                        # device hasn't run yet — the round-5 rehearsal
                        # measured 52k "img/s" that way.) First interval
                        # (compilation) excluded by construction.
                        now = time.perf_counter()
                        if items and log_t is not None:
                            rate = items * (step + 1 - log_step) / (now - log_t)
                            out["items_per_sec"] = round(rate, 2)
                            rate_trace.append(rate)
                        log_t, log_step = now, step + 1
                        logger.log(step + 1, out)
                    if should_save:
                        with obs.span("checkpoint_save"):
                            ckpt.save(step + 1, state)
                        # A new guard-passing checkpoint supersedes the
                        # poisoned-latest suspicion from a past restore.
                        restore_before = None
                if should_eval:
                    with obs.span("eval"):
                        last_eval = eval_hook(state)
                    if last_eval:
                        logger.log(
                            step + 1,
                            {"eval_" + k: v for k, v in last_eval.items()},
                        )
                step += 1
    finally:
        if tracing:  # run ended (or raised) inside the window
            jax.profiler.stop_trace()
        if handler_installed:
            # Restore unconditionally (getsignal-None priors included —
            # prev_handler None means "installed outside Python", and
            # SIG_DFL is the closest restorable equivalent).
            import signal

            signal.signal(
                signal.SIGTERM,
                prev_handler if prev_handler is not None else signal.SIG_DFL,
            )
    if ckpt:
        with obs.span("checkpoint_save", reason="final"):
            if (
                final_save
                and not preempted["flag"]
                and step > start_step
                and ckpt.latest_step() != step  # cadence already saved here
            ):
                ckpt.save(step, state)
            ckpt.wait()

    losses = [l for _, l in loss_trace]
    out = {
        "state": state,
        "steps": int(state.step),
        "losses": losses,
        "final_loss": losses[-1] if losses else float("nan"),
        "restores": restores,
        "preempted": preempted["flag"],
    }
    if rate_trace:
        # Best logged window ≈ uncontended throughput (same convention
        # as bench.py's best-of-N; the tunneled chip shows transient
        # multi-x slowdowns) — the e2e img/s the rehearsal script reads.
        out["items_per_sec"] = round(max(rate_trace), 2)
        out["items_per_sec_last"] = round(rate_trace[-1], 2)
    if last_eval:  # an empty sweep (val split < one batch) records nothing
        out["eval"] = last_eval
    if obs.enabled():
        # End-of-run roll-up (ISSUE 1 tentpole): phase totals + top
        # collectives by modeled wire bytes, logged so the JSONL stream
        # carries the breakdown, and attached to the result for callers
        # (bench, rehearsal scripts) to persist. The full timeline is
        # the caller's to export (obs.export_chrome_trace).
        out["obs"] = obs.summary()
        totals = {
            f"obs_{name}_total_s": round(p["total_s"], 4)
            for name, p in out["obs"]["phases"].items()
        }
        if totals:
            logger.log(step, {"event": "obs_summary", **totals})
    return out


class Trainer:
    """Drive ``step_fn`` over a data stream with logging and checkpoints.

    Args:
      world: communication World.
      state: initial TrainState (from ``make_train_step``'s init_fn, or a
        checkpoint restore).
      step_fn: jitted ``(state, batch) -> (state, metrics)``.
      batches: host-side batch iterator (numpy pytrees); sharded and
        prefetched internally.
      items_per_batch: global batch size, for the items/sec meter.
      log_every: metric log interval (steps).
      logger: MetricLogger (default: stdout only).
      checkpoint: optional (CheckpointManager, save_every) pair.
      hooks: callables ``hook(step, state, metrics)`` run at log points.
    """

    def __init__(
        self,
        world,
        state: TrainState,
        step_fn: Callable,
        batches: Iterator,
        *,
        items_per_batch: int | None = None,
        log_every: int = 50,
        logger: MetricLogger | None = None,
        checkpoint: tuple[Any, int] | None = None,
        hooks: list[Callable] | None = None,
        axis: str = "data",
    ):
        self.world = world
        self.state = state
        self._step_fn = step_fn
        self._batches = batches
        self._items = items_per_batch
        self._log_every = log_every
        self._logger = logger or MetricLogger()
        self._ckpt = checkpoint
        self._hooks = hooks or []
        self._axis = axis
        self._throughput = Throughput()

    @property
    def step(self) -> int:
        return int(self.state.step)

    def train(self, num_steps: int) -> dict[str, float]:
        """Run ``num_steps`` steps; returns the last logged metrics."""
        last: dict[str, float] = {}
        # Host-side step counter: reading state.step every iteration would
        # block dispatch on the just-enqueued step and serialize host/device.
        step = int(self.state.step)
        tick_step = step
        with Prefetcher(self.world, self._batches, axis=self._axis) as stream:
            for _ in range(num_steps):
                batch = next(stream)
                self.state, metrics = self._step_fn(self.state, batch)
                step += 1
                if step % self._log_every == 0 or step == 1:
                    # device sync happens here (float() blocks on the step)
                    last = {k: float(v) for k, v in metrics.items()}
                    if self._items is not None:
                        rate = self._throughput.tick(
                            self._items * (step - tick_step)
                        )
                        tick_step = step
                        if rate is not None:
                            last["items_per_sec"] = rate
                    self._logger.log(step, last)
                    for hook in self._hooks:
                        hook(step, self.state, last)
                if self._ckpt is not None:
                    mgr, every = self._ckpt
                    if step % every == 0:
                        mgr.save(step, self.state)
        return last

    def evaluate(
        self, eval_step: Callable, batches: Iterator, num_batches: int
    ) -> dict[str, float]:
        """Average ``eval_step`` metrics over ``num_batches``."""
        totals: dict[str, float] = {}
        with Prefetcher(self.world, batches, axis=self._axis) as stream:
            for _ in range(num_batches):
                metrics = eval_step(self.state, next(stream))
                for k, v in metrics.items():
                    totals[k] = totals.get(k, 0.0) + float(v)
        return {k: v / num_batches for k, v in totals.items()}
