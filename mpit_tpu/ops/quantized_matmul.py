"""Quantized int8 weight store + blocked fused-dequant matmul (ISSUE 17).

PR 15 halved the KV sweep and its own bench honesty note recorded the
real verdict: at serving batch sizes the KV cache is a sliver of tick
bytes (``total_bytes_ratio`` 0.9233) — **params dominate the decode HBM
sweep**. This module points the repo's one rounding contract
(:func:`mpit_tpu.ops.ring_collectives.quantize_blocks`, EQuARX-style
``amax/127`` round-half-to-even) at that dominant stream: matmul
weights stored as **int8 + one f32 scale per row**, dequantized per
row-block *inside* the matmul, so what crosses HBM→VMEM is int8 tiles
plus scale blocks — roughly half the f32 wire — and a full dequantized
weight array never exists.

Grain: one scale per leading row over the trailing features
(``quantize_blocks(w, axis=-1)``). For a projection kernel ``[D, F]``
that is one scale per *contraction* row, so a row-block tile carries its
own scales into the blocked ``x @ W``; for the LM head / embedding
``[V, D]`` it is one scale per vocab row, which is exactly the grain
``ops/lm_head.py``'s streamed vocab blocks consume.

Three matmul forms, one math:

- :func:`quantized_matmul` — ``x @ W`` for ``W`` ``[D, F]``, blocked
  over the contraction dim. On TPU a Pallas kernel DMAs int8 tiles +
  scale blocks HBM→VMEM on two channels (double-buffered, the PR 15
  decode-kernel pattern) and dequantizes per tile in VMEM with f32
  accumulation; off-TPU (and under ``interpret=None`` on CPU) the
  blocked lax path below runs the SAME per-tile dequant math — the
  kernel's numerical oracle, interpret-mode parity pinned (the PR 9/15
  discipline).
- :func:`quantized_matmul_t` — ``x @ W.T`` for ``W`` ``[V, D]`` (the
  in-model head einsum, e.g. the speculative draft's hot head pass),
  blocked over the *output* rows. Each output column still sees the
  full-D contraction, so this is bitwise identical to whole-dequant —
  blocking here is purely an intermediate-footprint discipline.
- :func:`quantized_matmul_reference` — whole-tensor dequant then plain
  matmul. The anti-vacuity oracle: it deliberately materializes the
  f32 weight, which is what the ``quantized-weights`` jaxpr contract
  proves the serving paths never do. Reference engines only.

:class:`QuantizedTensor` is the container — the ``QuantizedKV`` mold
(``ops/kv_quant.py``): a registered pytree ``(q int8 [..., rows, cols],
scale f32 [..., rows, 1])`` that rides through jit / shard_map /
device_put whole and drops into a flax param seat (the model's Dense
modules dispatch on it).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpit_tpu.ops.ring_collectives import (
    dequantize_blocks,
    quantize_blocks,
)

__all__ = [
    "QuantizedTensor",
    "dequantize_tensor",
    "quantize_tensor",
    "quantized_matmul",
    "quantized_matmul_lax",
    "quantized_matmul_reference",
    "quantized_matmul_t",
    "weight_wire_bytes",
]

# f32 scale per weight row: the store's fixed overhead (the
# ``kv_quant.SCALE_BYTES`` sibling at the weight grain).
SCALE_BYTES = 4

# Default contraction row-block. 256 f32 rows of the widest GPT-2 small
# kernel (d_ff 3072) is a ~3 MB f32 tile after dequant — comfortably
# VMEM-resident double-buffered — and a multiple of every TPU lane/
# sublane constraint the kernel needs.
DEFAULT_BLOCK_ROWS = 256

_LANE = 128
_SUBLANE_F32 = 8


def _round_up(x: int, m: int) -> int:
    return x + (-x) % m


def _use_kernel(interpret: bool | None) -> bool:
    if interpret is not None:
        return True
    return jax.devices()[0].platform == "tpu"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """One quantized weight: ``q`` int8 ``[..., rows, cols]`` plus
    ``scale`` f32 ``[..., rows, 1]`` (keepdims — equal rank, so
    shardings/masks written for the payload broadcast to both leaves).
    A pytree: q and scale ride together through jit / device_put /
    shard_map and through a flax param seat."""

    q: Any
    scale: Any

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    # Shape/dtype delegate to the int8 payload — geometry readers
    # (config inference, shape validation) see the logical weight; the
    # wire dtype IS int8.
    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def ndim(self):
        return self.q.ndim

    def __getitem__(self, idx):
        """Index q and scale together (the embedding-gather path:
        ``wte[tokens]`` picks int8 rows AND their scales)."""
        return QuantizedTensor(q=self.q[idx], scale=self.scale[idx])


def quantize_tensor(x) -> QuantizedTensor:
    """Quantize a weight ``[..., rows, cols]`` at one-scale-per-row
    grain through the shared
    :func:`~mpit_tpu.ops.ring_collectives.quantize_blocks` contract
    (amax/127, round-half-to-even, all-zero rows get scale 1.0 so they
    round-trip to exact zeros)."""
    q, scale = quantize_blocks(x, axis=-1)
    return QuantizedTensor(q=q, scale=scale)


def dequantize_tensor(t: QuantizedTensor):
    """Whole-tensor f32 view — oracle/reference use ONLY. Serving paths
    dequantize per row-block; the ``quantized-weights`` jaxpr contract
    fails any engine step that materializes this."""
    return dequantize_blocks(t.q, t.scale)


def weight_wire_bytes(shape, dtype) -> float:
    """HBM bytes one weight actually occupies on the wire — the
    :func:`~mpit_tpu.ops.kv_quant.kv_wire_bytes_per_row` sibling at the
    weight grain, shared by the roofline param term, the engine's
    ``decode_achieved_hbm_bytes`` and the bench capacity math. ``dtype``
    "int8" (or the int8 numpy dtype) = int8 payload + one f32 scale per
    leading row; anything else = the dense tensor in that dtype."""
    n = 1
    for s in shape:
        n *= int(s)
    if dtype == "int8" or jnp.dtype(dtype) == jnp.int8:
        rows = n // int(shape[-1]) if shape else 1
        return float(n + rows * SCALE_BYTES)
    return float(n * jnp.dtype(dtype).itemsize)


def _pad_blocks(w: QuantizedTensor, block: int):
    """Pad a quantized weight's rows to a multiple of ``block`` and
    reshape to per-block tiles: ``([n, block, cols] int8, [n, block]
    f32)``. Pad rows are zero with scale 1.0 — they dequantize to exact
    zeros and contribute nothing."""
    rows, cols = w.q.shape
    pad = (-rows) % block
    q, scale = w.q, w.scale
    if pad:
        q = jnp.concatenate([q, jnp.zeros((pad, cols), q.dtype)], axis=0)
        scale = jnp.concatenate(
            [scale, jnp.ones((pad, 1), scale.dtype)], axis=0
        )
    n = q.shape[0] // block
    return q.reshape(n, block, cols), scale.reshape(n, block)


def quantized_matmul_lax(x, w: QuantizedTensor, *, block_rows=None):
    """Blocked ``x @ W`` over the contraction dim, pure lax — the
    kernel's numerical oracle and the off-TPU fallback. Per scan tick
    ONE ``[block, F]`` tile is dequantized (f32) and contracted; the
    full f32 weight never exists. Returns f32 ``[..., F]``."""
    d, f = w.q.shape
    block = min(block_rows or DEFAULT_BLOCK_ROWS, _round_up(d, 8))
    qb, sb = _pad_blocks(w, block)
    n = qb.shape[0]
    pad = n * block - d
    x32 = x.astype(jnp.float32)
    if pad:
        x32 = jnp.concatenate(
            [x32, jnp.zeros((*x32.shape[:-1], pad), jnp.float32)], axis=-1
        )
    # [..., n, block] -> [n, ..., block]: the scan streams row-blocks.
    xb = jnp.moveaxis(
        x32.reshape(*x32.shape[:-1], n, block), -2, 0
    )

    def tick(acc, xs):
        q_i, s_i, x_i = xs
        w_i = dequantize_blocks(q_i, s_i[:, None])  # [block, F] f32
        part = lax.dot_general(
            x_i, w_i, (((x_i.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc + part, None

    acc0 = jnp.zeros((*x.shape[:-1], f), jnp.float32)
    acc, _ = lax.scan(tick, acc0, (qb, sb, xb), unroll=min(n, 8))
    return acc


def quantized_matmul_t(x, w: QuantizedTensor, *, block_rows=None):
    """Blocked ``x @ W.T`` for ``W`` ``[V, D]`` — the in-model head
    einsum (``"btd,vd->btv"``) against a quantized head/embedding.
    Blocks over the OUTPUT rows, so each logit column still sees the
    full-D contraction: bitwise identical to whole-dequant, with only a
    ``[block, D]`` f32 tile live. Returns f32 ``[..., V]``."""
    v, d = w.q.shape
    block = min(block_rows or DEFAULT_BLOCK_ROWS, _round_up(v, 8))
    qb, sb = _pad_blocks(w, block)
    n = qb.shape[0]
    x32 = x.astype(jnp.float32)

    def tick(_, xs):
        q_i, s_i = xs
        w_i = dequantize_blocks(q_i, s_i[:, None])  # [block, D] f32
        part = lax.dot_general(
            x32, w_i, (((x32.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return None, part

    _, parts = lax.scan(tick, None, (qb, sb), unroll=min(n, 8))
    # [n, ..., block] -> [..., n*block] -> drop pad cols.
    out = jnp.moveaxis(parts, 0, -2).reshape(*x.shape[:-1], n * block)
    return out[..., :v]


def quantized_matmul_reference(x, w: QuantizedTensor, *, block_rows=None):
    """Whole-dequant oracle: materializes the full f32 weight on
    purpose. This is what reference engines run (anti-vacuity for the
    jaxpr contract) and what parity tests pin the blocked paths
    against. Returns f32 ``[..., F]``."""
    del block_rows
    return lax.dot_general(
        x.astype(jnp.float32), dequantize_tensor(w),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


# ---------------------------------------------------------------------------
# Kernel. x resident in VMEM; the int8 row-block tiles and their scale
# blocks stay in HBM (memory_space=ANY) and are DMA'd in by the kernel
# on two channels of a double buffer — exactly the PR 15 quantized
# decode-attention transfer pattern, aimed at weights.
# ---------------------------------------------------------------------------


def _qmm_kernel(x_ref, q_hbm, s_hbm, o_ref, q_buf, s_buf, sem, *, n_blocks):
    """One program: ``o = Σ_i x[i] @ (q[i] · s[i])`` with f32 accumulate.

    ``x_ref`` [n, M, block] f32 VMEM (pre-blocked over the contraction
    dim); ``q_hbm`` [n, block, F] int8 / ``s_hbm`` [n, block] f32 in
    HBM; double-buffered VMEM scratch ``q_buf`` [2, block, F] /
    ``s_buf`` [2, block]; ``sem`` [2 channels, 2 slots] DMA semaphores.
    """

    def dma(i, slot):
        return (
            pltpu.make_async_copy(q_hbm.at[i], q_buf.at[slot], sem.at[0, slot]),
            pltpu.make_async_copy(s_hbm.at[i], s_buf.at[slot], sem.at[1, slot]),
        )

    for c in dma(0, 0):
        c.start()

    m, f = o_ref.shape

    def body(i, acc):
        slot = lax.rem(i, 2)

        @pl.when(i + 1 < n_blocks)
        def _prefetch():
            for c in dma(i + 1, 1 - slot):
                c.start()

        for c in dma(i, slot):
            c.wait()

        # Fused dequant in VMEM: the f32 weight exists only as this
        # [block, F] tile.
        w_tile = q_buf[slot].astype(jnp.float32) * s_buf[slot][:, None]
        return acc + jnp.dot(
            x_ref[i], w_tile, preferred_element_type=jnp.float32
        )

    acc = lax.fori_loop(
        0, n_blocks, body, jnp.zeros((m, f), jnp.float32)
    )
    o_ref[...] = acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def _qmm_call(x_blocked, q_blocked, s_blocked, *, interpret):
    n, m, _ = x_blocked.shape
    f = q_blocked.shape[-1]
    kern = functools.partial(_qmm_kernel, n_blocks=n)
    return pl.pallas_call(
        kern,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),  # x, whole [n, M, b]
            # int8 tiles + scale blocks stay in HBM; the kernel DMAs
            # them per row-block on two channels.
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, f), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, q_blocked.shape[1], f), jnp.int8),
            pltpu.VMEM((2, q_blocked.shape[1]), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
        interpret=bool(interpret),
    )(x_blocked, q_blocked, s_blocked)


def quantized_matmul(
    x, w: QuantizedTensor, *, block_rows=None, interpret: bool | None = None
):
    """``x @ W`` against an int8-per-row weight ``[D, F]`` — the serving
    matmul. TPU (or ``interpret=True``): the Pallas fused-dequant kernel
    above. Otherwise: :func:`quantized_matmul_lax`, the same per-tile
    math through the shared dequant helpers (the numerical oracle —
    interpret-mode parity is pinned in tests). Returns f32 ``[..., F]``
    (callers cast to their compute dtype; accumulation is f32 on every
    path)."""
    d, f = w.q.shape
    block = min(block_rows or DEFAULT_BLOCK_ROWS, _round_up(d, 8))
    # Kernel tile constraints: int8 min tile is (32, 128) and the
    # pre-blocked x slabs index the lane dim per block — anything
    # unaligned takes the lax path (same math, same rounding contract).
    aligned = (
        block % _LANE == 0 and f % _LANE == 0 and d % block == 0
    )
    if not (_use_kernel(interpret) and aligned):
        return quantized_matmul_lax(x, w, block_rows=block)
    n = d // block
    m = 1
    for s in x.shape[:-1]:
        m *= int(s)
    m_pad = _round_up(max(m, 1), _SUBLANE_F32)
    x2 = x.reshape(m, d).astype(jnp.float32)
    if m_pad != m:
        x2 = jnp.concatenate(
            [x2, jnp.zeros((m_pad - m, d), jnp.float32)], axis=0
        )
    # [M, D] -> [n, M, block]: each kernel tick reads one slab.
    xb = jnp.moveaxis(x2.reshape(m_pad, n, block), 1, 0)
    qb = w.q.reshape(n, block, f)
    sb = w.scale.reshape(n, block)
    out = _qmm_call(xb, qb, sb, interpret=interpret is True)
    return out[:m].reshape(*x.shape[:-1], f)
