"""Reusable jaxpr-contract assertions + the whole-package sweep.

ISSUE 14 pass 2: the repo pinned its "never materializes X" invariants
with per-test string/aval greps (``tests/test_serve.py``,
``tests/test_decode_attention.py`` each carried a private
``_avals_with_shape``). This module is the ONE audited implementation —
the tests now import it — plus a sweep that traces every registered
jitted step and checks its declared contracts, so a new code path that
re-materializes the ``[slots, vocab]`` logits fails tier-1 even if its
author never read the serving tests.

Library (works on a jaxpr, a ClosedJaxpr, or a callable + args):

- :func:`find_avals` — recursively collect eqn OUTPUT avals of a given
  shape (nested call/scan/cond/pallas jaxprs included); byte-compatible
  with the old test helpers.
- :func:`assert_no_intermediate` / :func:`assert_intermediate` — the
  materialization pin and its anti-vacuity twin ("the reference DOES
  materialize, so the pin means something").
- :func:`assert_no_transfer` — no ``device_put`` / host-callback
  primitives inside a step's jaxpr (a jitted hot-path step must not
  smuggle host round-trips).
- :func:`max_eqn_count` / :func:`eqn_count` — growth pin.
- :func:`donation_aliases` / :func:`assert_donation_consumed` — count
  ``tf.aliasing_output`` annotations in lowered StableHLO: donation
  that silently stopped applying (a dtype/shape change upstream) shows
  up as 2× transient HBM on the real chip.

The sweep (:func:`sweep`) builds tiny-config engines/steps on whatever
backend is present (tracing only — ``jax.make_jaxpr`` and ``.lower()``,
no kernel execution) and reports violations in the shared
:class:`~mpit_tpu.analysis.common.Violation` shape. Contracts are
REGISTERED (name → check) so ``--rule jaxpr-contracts`` can list and
subset them.
"""

from __future__ import annotations

from mpit_tpu.analysis.common import Violation, register_rule

R_JAXPR = register_rule(
    "jaxpr-contracts",
    "a registered jitted step violates its declared jaxpr contract "
    "(materialization / transfer / donation)",
)

__all__ = [
    "sub_jaxprs",
    "find_avals",
    "assert_no_intermediate",
    "assert_intermediate",
    "assert_no_transfer",
    "eqn_count",
    "max_eqn_count",
    "donation_aliases",
    "assert_donation_consumed",
    "sweep",
    "CONTRACTS",
]


class JaxprContractError(AssertionError):
    """A declared contract does not hold on the traced step."""


def _as_jaxpr(j):
    """Accept a ClosedJaxpr, a jaxpr, or anything carrying ``.jaxpr``."""
    return getattr(j, "jaxpr", j)


def sub_jaxprs(p):
    """Yield nested jaxprs reachable from an eqn param (closed jaxprs,
    raw jaxprs, and lists/tuples of either — scan/cond/pallas params)."""
    if hasattr(p, "jaxpr"):
        yield p.jaxpr
    elif hasattr(p, "eqns"):
        yield p
    elif isinstance(p, (list, tuple)):
        for q in p:
            yield from sub_jaxprs(q)


def find_avals(jaxpr, shape, prims=None, dtype=None):
    """Recursively collect eqn output avals of ``shape`` (incl. nested
    call/scan/cond jaxprs) — the materialization detector. Returns
    ``[(primitive_name, aval), ...]`` (the old test helpers' shape).
    ``prims`` optionally restricts to outputs of those primitives
    (e.g. ``{"dot_general"}`` pins "the logits matmul never runs at
    full width" while tolerating a full-width INPUT flowing through
    elementwise ops). ``dtype`` optionally restricts by element type —
    the quantized-decode contract (ISSUE 15) needs it: the int8 pool
    ITSELF legitimately has the pool shape, and only a float32 aval of
    that shape means the dequant escaped its tile."""
    jaxpr = _as_jaxpr(jaxpr)
    found = []
    for eqn in jaxpr.eqns:
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is not None and getattr(aval, "shape", None) == shape:
                if dtype is not None and getattr(
                    aval, "dtype", None
                ) != dtype:
                    continue
                if prims is None or eqn.primitive.name in prims:
                    found.append((eqn.primitive.name, aval))
        for p in eqn.params.values():
            for sub in sub_jaxprs(p):
                found.extend(find_avals(sub, shape, prims, dtype))
    return found


def assert_no_intermediate(jaxpr, *shapes, what="step", prims=None,
                           dtype=None):
    """No eqn output of any of ``shapes`` (of ``dtype``, when given)
    anywhere in the jaxpr."""
    for shape in shapes:
        hits = find_avals(jaxpr, tuple(shape), prims, dtype)
        if hits:
            raise JaxprContractError(
                f"{what} materializes {tuple(shape)}"
                f"{f' ({dtype})' if dtype is not None else ''}: "
                f"{[(p, str(a)) for p, a in hits[:4]]}"
            )


def assert_intermediate(jaxpr, shape, what="reference", dtype=None):
    """Anti-vacuity: the shape IS produced somewhere (so the matching
    ``assert_no_intermediate`` on the optimized path means something)."""
    if not find_avals(jaxpr, tuple(shape), None, dtype):
        raise JaxprContractError(
            f"{what} no longer materializes {tuple(shape)} — the "
            "no-materialization pin on the optimized path is vacuous"
        )


_TRANSFER_PRIMS = {
    "device_put",
    "pure_callback",
    "io_callback",
    "host_callback",
    "outside_call",
}


def _walk_eqns(jaxpr):
    jaxpr = _as_jaxpr(jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            for sub in sub_jaxprs(p):
                yield from _walk_eqns(sub)


def assert_no_transfer(jaxpr, what="step"):
    """No host-transfer / callback primitives inside the step."""
    bad = [
        e.primitive.name
        for e in _walk_eqns(jaxpr)
        if e.primitive.name in _TRANSFER_PRIMS
    ]
    if bad:
        raise JaxprContractError(
            f"{what} contains host-transfer primitives {sorted(set(bad))} "
            "— a jitted hot-path step must not smuggle host round-trips"
        )


def eqn_count(jaxpr) -> int:
    return sum(1 for _ in _walk_eqns(jaxpr))


def max_eqn_count(jaxpr, limit: int, what="step"):
    n = eqn_count(jaxpr)
    if n > limit:
        raise JaxprContractError(
            f"{what} grew to {n} eqns (pin: <= {limit}) — check for an "
            "unrolled loop or a duplicated subgraph"
        )


def donation_aliases(lowered_text: str) -> int:
    """Count donated inputs in lowered StableHLO. Two spellings on jax
    0.4.x: ``tf.aliasing_output`` when aliasing is resolved at lowering
    (single-device), ``jax.buffer_donor`` when it is deferred to
    compile (SPMD mesh) — both mean the input buffer is donated."""
    return lowered_text.count("tf.aliasing_output") + lowered_text.count(
        "jax.buffer_donor"
    )


def assert_donation_consumed(lowered_or_text, min_aliased: int = 1,
                             what="step"):
    txt = (
        lowered_or_text
        if isinstance(lowered_or_text, str)
        else lowered_or_text.as_text()
    )
    n = donation_aliases(txt)
    if n < min_aliased:
        raise JaxprContractError(
            f"{what} aliases only {n} donated inputs (pin: >= "
            f"{min_aliased}) — donation silently stopped applying "
            "(2x transient HBM for the state on chip)"
        )


# ---------------------------------------------------------------------------
# The whole-package sweep: registered steps × declared contracts.
# ---------------------------------------------------------------------------


def _tiny_model():
    import jax
    import jax.numpy as jnp

    from mpit_tpu.models import GPT2, GPT2Config

    cfg = GPT2Config.tiny(
        vocab_size=64, max_seq_len=64, num_layers=2, num_heads=2,
        d_model=32, dtype=jnp.float32,
    )
    model = GPT2(cfg)
    params = jax.jit(model.init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def _contract_decode_blocked(ctx):
    """Blocked head + flash decode: the [slots, vocab] f32 logits and
    the dense [slots, H, 1, max_len] score tensor never exist in the
    decode jaxpr — and the dense reference DOES produce them (the pin
    is non-vacuous). Also: no host-transfer primitives in the step."""
    import jax
    import jax.numpy as jnp

    from mpit_tpu.serve import Engine

    cfg, params = ctx["model"]
    slots, max_len = 2, 32
    eng = Engine(
        cfg, params, slots=slots, max_len=max_len, prefill_len=8,
        decode_attention="interpret", sample_block=32, sample_k_cap=16,
    )
    jx = jax.make_jaxpr(eng._decode_step)(
        eng.params, eng.cache, eng.last_token,
        jnp.ones((slots,), bool), jax.random.key(0),
        jnp.zeros((slots,), jnp.float32), jnp.zeros((slots,), jnp.int32),
    )
    assert_no_intermediate(
        jx,
        (slots, cfg.vocab_size),
        (slots, 1, cfg.vocab_size),
        (slots, cfg.num_heads, 1, max_len),
        what="blocked decode step",
    )
    assert_no_transfer(jx, what="blocked decode step")
    ref = Engine(
        cfg, params, slots=slots, max_len=max_len, prefill_len=8,
        decode_attention="reference",
    )
    jx_ref = jax.make_jaxpr(ref._decode_step)(
        ref.params, ref.cache, ref.last_token,
        jnp.ones((slots,), bool), jax.random.key(0),
        jnp.zeros((slots,), jnp.float32), jnp.zeros((slots,), jnp.int32),
    )
    assert_intermediate(
        jx_ref, (slots, 1, cfg.vocab_size), what="dense reference decode"
    )


def _contract_paged_decode_blocked(ctx):
    """The blocked-logits pin survives paging (ISSUE 7 regression
    surface: the paged decode step is a different trace)."""
    import jax
    import jax.numpy as jnp

    from mpit_tpu.serve import Engine

    cfg, params = ctx["model"]
    slots = 2
    eng = Engine(
        cfg, params, slots=slots, max_len=40, prefill_len=8,
        kv_pages=24, kv_page_size=8, decode_attention="interpret",
        sample_block=32, sample_k_cap=16,
    )
    bt = jnp.zeros((slots, eng.pages_per_slot), jnp.int32)
    jx = jax.make_jaxpr(eng._paged_decode_step)(
        eng.params, eng.cache, eng.last_token,
        jnp.ones((slots,), bool), bt, jax.random.key(0),
        jnp.zeros((slots,), jnp.float32), jnp.zeros((slots,), jnp.int32),
    )
    assert_no_intermediate(
        jx,
        (slots, cfg.vocab_size),
        (slots, 1, cfg.vocab_size),
        (slots, cfg.num_heads, 1, eng.max_len),
        what="paged decode step",
    )
    assert_no_transfer(jx, what="paged decode step")


def _contract_quantized_decode(ctx):
    """ISSUE 15: the int8 KV cache's dequant stays PER-TILE inside the
    decode kernel — no full dequantized f32 pool (or per-slot dense
    view) intermediate may materialize in the quantized decode step's
    jaxpr. The int8 pool itself legitimately carries the pool shape, so
    the pin is dtype-filtered to float32. Anti-vacuity: the reference
    engine (the parity oracle) DOES materialize the dequantized f32
    view — the pin means something."""
    import jax
    import jax.numpy as jnp

    from mpit_tpu.serve import Engine

    cfg, params = ctx["model"]
    slots, pages, ps = 2, 24, 8
    eng = Engine(
        cfg, params, slots=slots, max_len=40, prefill_len=8,
        kv_pages=pages, kv_page_size=ps, decode_attention="interpret",
        sample_block=32, sample_k_cap=16, kv_dtype="int8",
    )
    bt = jnp.zeros((slots, eng.pages_per_slot), jnp.int32)
    jx = jax.make_jaxpr(eng._paged_decode_step)(
        eng.params, eng.cache, eng.last_token,
        jnp.ones((slots,), bool), bt, jax.random.key(0),
        jnp.zeros((slots,), jnp.float32), jnp.zeros((slots,), jnp.int32),
    )
    f32 = jnp.dtype(jnp.float32)
    pool = (pages, ps, cfg.num_heads, cfg.head_dim)
    assert_no_intermediate(
        jx,
        pool,                              # one layer's dequantized pool
        (cfg.num_layers,) + pool,          # the stacked pools
        (slots, eng.pages_per_slot * ps,   # a slot's gathered dense view
         cfg.num_heads, cfg.head_dim),
        what="quantized paged decode step",
        dtype=f32,
    )
    # The [slots, vocab] pin survives quantization too.
    assert_no_intermediate(
        jx, (slots, cfg.vocab_size), (slots, 1, cfg.vocab_size),
        what="quantized paged decode step",
    )
    ref = Engine(
        cfg, params, slots=slots, max_len=40, prefill_len=8,
        kv_pages=pages, kv_page_size=ps, decode_attention="reference",
        kv_dtype="int8",
    )
    jx_ref = jax.make_jaxpr(ref._paged_decode_step)(
        ref.params, ref.cache, ref.last_token,
        jnp.ones((slots,), bool), bt, jax.random.key(0),
        jnp.zeros((slots,), jnp.float32), jnp.zeros((slots,), jnp.int32),
    )
    assert_intermediate(
        jx_ref,
        (slots, eng.pages_per_slot * ps, cfg.num_heads, cfg.head_dim),
        what="quantized reference decode (dequant oracle)",
        dtype=f32,
    )
    # Dense form: the quantized dense step never materializes the f32
    # per-slot buffer either (its int8 buffer carries the shape).
    dense = Engine(
        cfg, params, slots=slots, max_len=32, prefill_len=8,
        decode_attention="interpret", sample_block=32, sample_k_cap=16,
        kv_dtype="int8",
    )
    jxd = jax.make_jaxpr(dense._decode_step)(
        dense.params, dense.cache, dense.last_token,
        jnp.ones((slots,), bool), jax.random.key(0),
        jnp.zeros((slots,), jnp.float32), jnp.zeros((slots,), jnp.int32),
    )
    assert_no_intermediate(
        jxd,
        (slots, 32, cfg.num_heads, cfg.head_dim),
        (cfg.num_layers, slots, 32, cfg.num_heads, cfg.head_dim),
        what="quantized dense decode step",
        dtype=f32,
    )


def _contract_quantized_weights(ctx):
    """ISSUE 17: the int8 weight store's dequant stays PER-BLOCK inside
    the blocked matmuls — no full dequantized f32 weight (qkv/proj/fc/
    out kernel, wte / tied head) may materialize in any int8 engine
    step's jaxpr. The contract shrinks the tile grain
    (``quant_block_rows=16``, ``sample_block=16``) so a LEGITIMATE
    dequantized tile can never collide with a pinned full-weight shape
    on the tiny config (e.g. a 32-row head tile would equal the 32x32
    proj kernel). Both hot traces are pinned: the plain decode step and
    the speculative draft step (whose head runs INSIDE the hot tick —
    the trace a whole-dequant shortcut would most plausibly sneak back
    through). Anti-vacuity: the reference engine (the whole-dequant
    parity oracle) DOES materialize the f32 qkv kernel."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from mpit_tpu.serve import Engine
    from mpit_tpu.serve.weights import draft_from_target

    cfg, params = ctx["model"]
    cfg16 = dataclasses.replace(cfg, quant_block_rows=16)
    slots, max_len = 2, 32
    f32 = jnp.dtype(jnp.float32)
    weights = (
        (cfg.d_model, 3 * cfg.d_model),  # qkv kernel
        (cfg.d_model, cfg.d_model),      # proj kernel
        (cfg.d_model, cfg.ff_dim),       # fc kernel
        (cfg.ff_dim, cfg.d_model),       # out kernel
        (cfg.vocab_size, cfg.d_model),   # wte / tied head
    )

    def decode_jaxpr(eng):
        return jax.make_jaxpr(eng._decode_step)(
            eng.params, eng.cache, eng.last_token,
            jnp.ones((slots,), bool), jax.random.key(0),
            jnp.zeros((slots,), jnp.float32),
            jnp.zeros((slots,), jnp.int32),
        )

    eng = Engine(
        cfg16, params, slots=slots, max_len=max_len, prefill_len=8,
        decode_attention="interpret", sample_block=16, sample_k_cap=16,
        weights_dtype="int8",
    )
    assert_no_intermediate(
        decode_jaxpr(eng), *weights,
        what="int8-weights decode step", dtype=f32,
    )
    dp, dcfg = draft_from_target(params, cfg16, 1)
    spec = Engine(
        cfg16, params, slots=slots, max_len=max_len, prefill_len=8,
        decode_attention="interpret", sample_block=16, sample_k_cap=16,
        spec_k=2, draft_params=dp, draft_cfg=dcfg, weights_dtype="int8",
    )
    jxd = jax.make_jaxpr(spec._spec_draft_step)(
        spec.draft_params, spec.draft_cache, spec.last_token,
        jnp.ones((slots,), bool), jax.random.key(0),
        jnp.zeros((slots,), jnp.float32), jnp.zeros((slots,), jnp.int32),
    )
    assert_no_intermediate(
        jxd, *weights, what="int8-weights spec_draft step", dtype=f32
    )
    ref = Engine(
        cfg, params, slots=slots, max_len=max_len, prefill_len=8,
        decode_attention="reference", weights_dtype="int8",
    )
    assert_intermediate(
        decode_jaxpr(ref), (cfg.d_model, 3 * cfg.d_model),
        what="int8-weights reference decode (whole-dequant oracle)",
        dtype=f32,
    )


def _contract_lm_head_sample(ctx):
    """The blocked sampler never runs the full-width logits matmul."""
    import jax
    import jax.numpy as jnp

    from mpit_tpu.ops.lm_head import lm_head_sample

    del ctx
    S, V, D = 5, 256, 16
    h = jnp.zeros((S, D), jnp.float32)
    head = jnp.zeros((V, D), jnp.float32)
    temp = jnp.ones((S,), jnp.float32)
    topk = jnp.zeros((S,), jnp.int32)
    jx = jax.make_jaxpr(
        lambda h, w: lm_head_sample(
            h, w, jax.random.key(0), temp, topk, block_size=64
        )
    )(h, head)
    assert_no_intermediate(jx, (S, V), what="lm_head_sample")


def _contract_lm_head_verify(ctx):
    """The speculative verifier's logits matmul never runs at full
    vocab width (qprobs legitimately ENTERS at [N, vocab]; the pin is
    on dot_general outputs — the blocked two-pass contract)."""
    import jax
    import jax.numpy as jnp

    from mpit_tpu.ops.lm_head import lm_head_verify

    del ctx
    N, V, D = 4, 256, 16
    jx = jax.make_jaxpr(
        lambda h, w, q: lm_head_verify(
            h, w, jnp.zeros((N,), jnp.int32), q, jax.random.key(0),
            jnp.ones((N,), jnp.float32), jnp.zeros((N,), jnp.int32),
            block_size=64, k_cap=8,
        )
    )(
        jnp.zeros((N, D), jnp.float32),
        jnp.zeros((V, D), jnp.float32),
        jnp.zeros((N, V), jnp.float32),
    )
    assert_no_intermediate(
        jx, (N, V), what="lm_head_verify", prims={"dot_general"}
    )


def _contract_train_step_donation(ctx):
    """The production train step still donates (and aliases) its state
    buffers — the in-place-update contract that keeps peak HBM at 1x
    state. Lowering only; nothing is compiled or run."""
    import jax.numpy as jnp
    import numpy as np
    import optax

    from mpit_tpu import comm
    from mpit_tpu.train.step import make_train_step

    del ctx
    world = comm.init(set_default=False)

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    init_fn, step_fn, _specs = make_train_step(
        loss_fn, optax.sgd(1e-2), world, zero1=False
    )
    n = world.axis_size("data")
    params = {"w": jnp.zeros((8, 4), jnp.float32)}
    state = init_fn(params)
    batch = {
        "x": np.zeros((2 * n, 8), np.float32),
        "y": np.zeros((2 * n, 4), np.float32),
    }
    from mpit_tpu.data.loader import shard_batch

    device_batch = shard_batch(world, batch, axis="data")
    jitted = step_fn.build(state.params, state.extra)
    lowered = jitted.lower(state, device_batch)
    assert_donation_consumed(lowered, min_aliased=2, what="train step")


CONTRACTS = {
    "decode-blocked": _contract_decode_blocked,
    "paged-decode-blocked": _contract_paged_decode_blocked,
    "quantized-decode": _contract_quantized_decode,
    "quantized-weights": _contract_quantized_weights,
    "lm-head-sample": _contract_lm_head_sample,
    "lm-head-verify": _contract_lm_head_verify,
    "train-step-donation": _contract_train_step_donation,
}


def sweep(names=None) -> list:
    """Trace every registered step and check its contracts. Shared
    tiny-model context is built once. Returns Violations (one per
    failed contract; a contract that ERRORS — API drift, import
    failure — is also a violation: the pin went dark, which is exactly
    what the sweep exists to catch)."""
    out = []
    ctx: dict = {}
    try:
        ctx["model"] = _tiny_model()
    except Exception as e:  # pragma: no cover - environment failure
        return [
            Violation(
                R_JAXPR, __file__, 0,
                f"sweep context failed to build: {type(e).__name__}: {e}",
            )
        ]
    for name, fn in CONTRACTS.items():
        if names is not None and name not in names:
            continue
        try:
            fn(ctx)
        except JaxprContractError as e:
            out.append(Violation(R_JAXPR, __file__, 0, f"{name}: {e}"))
        except Exception as e:
            out.append(
                Violation(
                    R_JAXPR, __file__, 0,
                    f"{name}: contract errored ({type(e).__name__}: {e}) "
                    "— the pin went dark; update the contract with the "
                    "API it pins",
                )
            )
    return out
