"""Bounded-memory streaming metrics: quantile sketch + rolling windows.

The Recorder (``obs.core``) retains every span and rolls percentiles up
once, at end of run — exactly right for a bench window, exactly wrong
for a sustained serving run: a multi-minute load test exhausts
``max_events`` and the "percentiles" silently describe a truncated
prefix (ISSUE 6 motivation). This module is the streaming counterpart
the serve path feeds per request/tick:

- :class:`HistogramSketch` — a log-bucketed quantile sketch in the
  DDSketch family (arXiv 1908.10693): geometric buckets with ratio
  ``gamma = (1+a)/(1-a)`` hold counts, so any quantile is answered with
  relative error ≤ ``a`` (default 1%) from O(buckets) memory, values
  never retained. Sketches over the same ``rel_err`` MERGE by adding
  bucket counts — the property the rolling window and any future
  cross-rank aggregation are built on. Pinned against a numpy oracle
  across adversarial distributions in ``tests/test_stream.py``.
- :class:`WindowedHistogram` — a ring of per-interval sub-sketches;
  ``quantile()`` merges the live intervals, so "p95 TTFT over the last
  10 s" costs O(buckets) and old traffic ages out by bucket, not by
  event.
- :class:`StreamRegistry` — the named-metric surface the serve
  scheduler feeds: windowed histograms (``observe``), windowed rates
  (``inc``), last-value gauges (``set_gauge``), one ``window_stats()``
  roll-up for the live stats line and the SLO monitor (``obs.slo``).

Everything is host-side pure Python + math (no numpy in the hot path,
no jax) — one ``observe`` is a log, a dict increment, and a ring-slot
check.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Iterable, Mapping

__all__ = ["HistogramSketch", "StreamRegistry", "WindowedHistogram"]


class HistogramSketch:
    """Mergeable log-bucketed quantile sketch for non-negative values.

    Bucket ``i`` covers ``(gamma**(i-1), gamma**i]`` with
    ``gamma = (1 + rel_err) / (1 - rel_err)``; the representative value
    ``2 * gamma**i / (gamma + 1)`` (the geometric midpoint) is within
    ``rel_err`` of every value in the bucket — the quantile-error
    guarantee. Values ``<= min_value`` land in a dedicated zero bucket
    (durations of 0.0 are legal and must not take a log).

    Memory is O(distinct buckets): a span of values covering 1 µs..100 s
    at 1% relative error is ~900 buckets, independent of how many
    billions of observations land in them.
    """

    __slots__ = ("rel_err", "min_value", "_gamma", "_log_gamma", "buckets",
                 "zero_count", "count", "sum", "min", "max")

    def __init__(self, *, rel_err: float = 0.01, min_value: float = 1e-9):
        if not 0.0 < rel_err < 1.0:
            raise ValueError(f"rel_err must be in (0, 1), got {rel_err}")
        self.rel_err = rel_err
        self.min_value = min_value
        self._gamma = (1.0 + rel_err) / (1.0 - rel_err)
        self._log_gamma = math.log(self._gamma)
        self.buckets: dict[int, int] = {}
        self.zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- recording ----------------------------------------------------------
    def add(self, value: float, n: int = 1) -> None:
        if value < 0.0:
            raise ValueError(
                f"HistogramSketch holds non-negative values (durations, "
                f"rates); got {value}"
            )
        self.count += n
        self.sum += value * n
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        if value <= self.min_value:
            self.zero_count += n
            return
        idx = math.ceil(math.log(value) / self._log_gamma)
        self.buckets[idx] = self.buckets.get(idx, 0) + n

    def merge(self, other: "HistogramSketch") -> "HistogramSketch":
        """Fold ``other`` into ``self`` (returns self). Requires equal
        ``rel_err`` — bucket indices are only meaningful per gamma."""
        if other.rel_err != self.rel_err:
            raise ValueError(
                f"cannot merge sketches with different rel_err "
                f"({self.rel_err} vs {other.rel_err})"
            )
        for idx, n in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + n
        self.zero_count += other.zero_count
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def copy(self) -> "HistogramSketch":
        out = HistogramSketch(rel_err=self.rel_err, min_value=self.min_value)
        out.buckets = dict(self.buckets)
        out.zero_count = self.zero_count
        out.count = self.count
        out.sum = self.sum
        out.min = self.min
        out.max = self.max
        return out

    # -- reading ------------------------------------------------------------
    def quantile(self, q: float) -> float | None:
        """The value at quantile ``q`` (0..1), within ``rel_err``
        relative error of the true order statistic; ``None`` when
        empty. The returned value is clamped to the observed
        ``[min, max]`` so bucket-midpoint rounding can never report a
        value outside the data."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.count == 0:
            return None
        rank = q * (self.count - 1)
        if rank < self.zero_count:
            return min(max(0.0, self.min), self.max)
        seen = self.zero_count
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            if seen > rank:
                mid = 2.0 * self._gamma ** idx / (self._gamma + 1.0)
                return min(max(mid, self.min), self.max)
        return self.max  # float accumulation fell one short: top bucket

    def mean(self) -> float | None:
        return self.sum / self.count if self.count else None

    def summary(self, quantiles: Iterable[float] = (0.5, 0.95)) -> dict:
        """``{count, mean, min, max, p50, p95, ...}`` (empty: count 0)."""
        if self.count == 0:
            return {"count": 0}
        out = {
            "count": self.count,
            "mean": self.sum / self.count,
            "min": self.min,
            "max": self.max,
        }
        for q in quantiles:
            out[f"p{round(q * 100):d}"] = self.quantile(q)
        return out


class WindowedHistogram:
    """A rolling time window over a :class:`HistogramSketch`.

    The window is ``intervals`` sub-sketches of ``interval_s`` seconds
    each (total span ``intervals * interval_s``); an observation lands
    in the sub-sketch of its interval, and a query merges the sub-
    sketches still inside the window — old traffic expires a whole
    interval at a time, which is the usual sliding-window-counter
    trade: the window edge is quantized to ``interval_s``, memory is
    bounded at ``intervals`` sketches regardless of run length.

    Timestamps are caller-supplied seconds (any monotonic epoch;
    ``time.perf_counter()`` in production, hand-rolled in tests).
    """

    __slots__ = ("interval_s", "intervals", "rel_err", "_ring", "_total")

    def __init__(
        self,
        *,
        window_s: float = 10.0,
        intervals: int = 10,
        rel_err: float = 0.01,
    ):
        if window_s <= 0 or intervals < 1:
            raise ValueError(
                f"need window_s > 0 and intervals >= 1, got "
                f"{window_s}/{intervals}"
            )
        self.interval_s = window_s / intervals
        self.intervals = intervals
        self.rel_err = rel_err
        # ring: slot -> (interval_index, sketch); lazily (re)filled.
        self._ring: dict[int, tuple[int, HistogramSketch]] = {}
        # All-time sketch: the closed-loop/end-of-run view, and the
        # "windowed vs exact" acceptance comparison's subject.
        self._total = HistogramSketch(rel_err=rel_err)

    def _slot(self, t: float) -> tuple[int, HistogramSketch]:
        idx = int(t // self.interval_s)
        slot = idx % self.intervals
        cur = self._ring.get(slot)
        if cur is None or cur[0] != idx:
            cur = (idx, HistogramSketch(rel_err=self.rel_err))
            self._ring[slot] = cur
        return cur

    def observe(self, value: float, t: float) -> None:
        self._slot(t)[1].add(value)
        self._total.add(value)

    def _live(self, now: float) -> Iterable[HistogramSketch]:
        lo = int(now // self.interval_s) - self.intervals + 1
        for idx, sk in self._ring.values():
            if idx >= lo:
                yield sk

    def window_sketch(self, now: float) -> HistogramSketch:
        """Merged sketch of the observations inside the window at
        ``now`` (O(intervals · buckets))."""
        out = HistogramSketch(rel_err=self.rel_err)
        for sk in self._live(now):
            out.merge(sk)
        return out

    def quantile(self, q: float, now: float) -> float | None:
        return self.window_sketch(now).quantile(q)

    def count(self, now: float) -> int:
        return sum(sk.count for sk in self._live(now))

    @property
    def total(self) -> HistogramSketch:
        return self._total


class _WindowedRate:
    """Per-interval event counts; ``rate()`` = window count / window
    span (the span actually covered, so early-run rates aren't diluted
    by not-yet-elapsed window)."""

    __slots__ = ("interval_s", "intervals", "_ring", "_t0", "total")

    def __init__(self, *, window_s: float, intervals: int):
        self.interval_s = window_s / intervals
        self.intervals = intervals
        self._ring: dict[int, tuple[int, float]] = {}
        self._t0: float | None = None
        self.total = 0.0

    def inc(self, value: float, t: float) -> None:
        if self._t0 is None:
            self._t0 = t
        self.total += value
        idx = int(t // self.interval_s)
        slot = idx % self.intervals
        cur = self._ring.get(slot)
        if cur is None or cur[0] != idx:
            cur = (idx, 0.0)
        self._ring[slot] = (idx, cur[1] + value)

    def window_total(self, now: float) -> float:
        lo = int(now // self.interval_s) - self.intervals + 1
        return sum(v for idx, v in self._ring.values() if idx >= lo)

    def rate(self, now: float) -> float:
        span = self.intervals * self.interval_s
        if self._t0 is not None:
            span = min(span, max(now - self._t0, self.interval_s))
        return self.window_total(now) / span


class StreamRegistry:
    """Named windowed metrics — the serve path's live telemetry surface.

    One registry per server run. Three metric kinds:

    - ``observe(name, value)`` — a windowed histogram (latencies,
      queue waits): ``quantile(name, q)`` answers over the rolling
      window, ``.total_sketch(name)`` over the whole run;
    - ``inc(name, value)`` — a windowed rate (requests, tokens, sheds):
      ``rate(name)`` is per-second over the window;
    - ``set_gauge(name, value)`` — last value (queue depth, occupancy).

    ``now``/``t`` default to ``clock()`` (``time.perf_counter``);
    tests pass explicit times for determinism. ``window_stats()`` is
    the one-call roll-up the CLI's live line and the SLO monitor read.
    """

    def __init__(
        self,
        *,
        window_s: float = 10.0,
        intervals: int = 10,
        rel_err: float = 0.01,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self.window_s = window_s
        self.intervals = intervals
        self.rel_err = rel_err
        self.clock = clock
        self._hists: dict[str, WindowedHistogram] = {}
        self._rates: dict[str, _WindowedRate] = {}
        self._gauges: dict[str, float] = {}

    # -- feeding ------------------------------------------------------------
    def observe(self, name: str, value: float, t: float | None = None) -> None:
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = WindowedHistogram(
                window_s=self.window_s, intervals=self.intervals,
                rel_err=self.rel_err,
            )
        h.observe(value, self.clock() if t is None else t)

    def inc(self, name: str, value: float = 1.0, t: float | None = None) -> None:
        r = self._rates.get(name)
        if r is None:
            r = self._rates[name] = _WindowedRate(
                window_s=self.window_s, intervals=self.intervals
            )
        r.inc(value, self.clock() if t is None else t)

    def set_gauge(self, name: str, value: float) -> None:
        self._gauges[name] = float(value)

    # -- reading ------------------------------------------------------------
    def quantile(self, name: str, q: float, now: float | None = None):
        h = self._hists.get(name)
        if h is None:
            return None
        return h.quantile(q, self.clock() if now is None else now)

    def window_count(self, name: str, now: float | None = None) -> int:
        h = self._hists.get(name)
        if h is None:
            return 0
        return h.count(self.clock() if now is None else now)

    def rate(self, name: str, now: float | None = None) -> float:
        r = self._rates.get(name)
        if r is None:
            return 0.0
        return r.rate(self.clock() if now is None else now)

    def window_total(self, name: str, now: float | None = None) -> float:
        r = self._rates.get(name)
        if r is None:
            return 0.0
        return r.window_total(self.clock() if now is None else now)

    def counter_total(self, name: str) -> float:
        r = self._rates.get(name)
        return r.total if r is not None else 0.0

    def gauge(self, name: str) -> float | None:
        return self._gauges.get(name)

    def total_sketch(self, name: str) -> HistogramSketch | None:
        h = self._hists.get(name)
        return h.total if h is not None else None

    def window_stats(self, now: float | None = None) -> dict:
        """``{"histograms": {name: {count, p50, p95}}, "rates":
        {name: {rate_per_s, window_total}}, "gauges": {...}}`` over the
        rolling window at ``now`` — the live stats line's payload."""
        now = self.clock() if now is None else now
        hists = {}
        for name, h in sorted(self._hists.items()):
            sk = h.window_sketch(now)
            entry: dict = {"count": sk.count}
            if sk.count:
                entry["p50"] = sk.quantile(0.5)
                entry["p95"] = sk.quantile(0.95)
            hists[name] = entry
        rates = {
            name: {
                "rate_per_s": r.rate(now),
                "window_total": r.window_total(now),
            }
            for name, r in sorted(self._rates.items())
        }
        return {
            "histograms": hists,
            "rates": rates,
            "gauges": dict(sorted(self._gauges.items())),
        }
