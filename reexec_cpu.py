"""Early pytest plugin: re-exec the test run onto a fake 8-device CPU mesh.

Loaded via ``pytest.ini`` ``addopts = -p reexec_cpu`` so it runs at plugin-
registration time — BEFORE pytest's fd-level capture starts — which keeps
the re-exec'd child's output on the real stdout. (``tests/conftest.py`` has
a fallback for runs that bypass pytest.ini, but by then capture has started
and the child's output is swallowed; this plugin is the primary path.)

Why re-exec at all: this environment's sitecustomize eagerly registers and
initializes the single-chip ``axon`` TPU backend in every Python process, so
in-process env changes are too late. The collective/sharding test suite
needs the fake 8-device CPU mesh (SURVEY.md §5.2) — the analogue of the
reference running MPI locally under ``mpirun -n 2..4`` (SURVEY.md §5.1).

Set ``MPIT_TEST_PLATFORM=axon`` to run on the real chip instead.
"""

import os
import sys

N_FAKE_DEVICES = 8


_COUNT_FLAG = r"--xla_force_host_platform_device_count=(\d+)"


def cpu_mesh_env(n_devices: int | None = None) -> dict:
    """A copy of ``os.environ`` rewritten for a fake-CPU-mesh child process.

    Strips ``PALLAS_AXON_POOL_IPS`` (the sitecustomize trigger that force-
    registers the single-chip axon backend and overrides ``JAX_PLATFORMS``)
    and sets the host-platform device count. An explicit ``n_devices``
    replaces any pre-existing ``xla_force_host_platform_device_count`` flag;
    ``None`` preserves a caller-supplied count (defaulting to
    ``N_FAKE_DEVICES`` when none is set).
    """
    import re

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disables axon registration
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if n_devices is None:
        m = re.search(_COUNT_FLAG, flags)
        n_devices = int(m.group(1)) if m else N_FAKE_DEVICES
    flags = re.sub(_COUNT_FLAG, "", flags)
    flags += f" --xla_force_host_platform_device_count={n_devices}"
    env["XLA_FLAGS"] = flags.strip()
    return env


def reexec_onto_cpu_mesh_if_needed() -> None:
    if os.environ.get("MPIT_TEST_REEXEC") == "1":
        return
    if os.environ.get("MPIT_TEST_PLATFORM", "cpu") != "cpu":
        return
    env = cpu_mesh_env()  # None: honor a caller-supplied device count
    env["MPIT_TEST_REEXEC"] = "1"
    sys.stdout.flush()
    sys.stderr.flush()
    os.execve(sys.executable, [sys.executable, "-m", "pytest", *sys.argv[1:]], env)


# Auto-run only when pytest is actually driving this process (the
# ``-p reexec_cpu`` early-plugin path: argv[0] is the pytest console script
# or pytest's __main__.py under ``python -m pytest``). Checking for pytest
# in sys.modules is NOT enough — any program that merely imported pytest
# would be silently exec'd into a test run when it imports this module —
# and a bare substring match on the path would hijack unrelated scripts
# that merely live under a pytest-named directory.
_argv0 = sys.argv[0]
if os.path.basename(_argv0).startswith(("pytest", "py.test")) or _argv0.endswith(
    os.path.join("pytest", "__main__.py")
):
    reexec_onto_cpu_mesh_if_needed()
