"""Pallas ring allreduce — the native-tier ``MPI_Allreduce``.

The reference's allreduce hot path is ``mpiT.Allreduce`` → ``MPI_Allreduce``
→ libmpi's ring/tree (SURVEY.md §4.3). The XLA tier
(``comm.collectives.allreduce`` = ``lax.psum``) already lowers to an ICI
ring; this module is the hand-scheduled equivalent — the kernel the
"allreduce GB/s" benchmark measures and the in-tree proof that the
framework owns its communication stack down to the DMA level.

Algorithm (classic two-phase ring, bandwidth-optimal 2·(P-1)/P · N):

1. **Reduce-scatter** (P-1 steps): the payload is split into P chunks; at
   step s every device sends its running sum of chunk ``(i-s) mod P`` one
   hop clockwise through a double-buffered VMEM mailbox
   (``make_async_remote_copy``) and adds the chunk arriving from its left
   neighbor. After P-1 steps device i holds the fully-reduced chunk
   ``(i+1) mod P``.
2. **All-gather** (P-1 steps): the owned chunks circulate; each arriving
   chunk is copied from the mailbox into its slot of the output.

Synchronization discipline (pinned down by tests/test_ops.py in TPU
interpret mode):
- a neighbor barrier (``get_barrier_semaphore``) before the first send, so
  no device writes into a mailbox that is not yet live;
- remote writes land ONLY in the double-buffered receive mailbox
  (``recv_buf``); the send staging buffer (``send_buf``) is strictly
  device-local, so an early neighbor can never clobber a send in flight;
- ``rdma.wait()`` blocks on both the local send completion (making
  ``send_buf`` safe to restage next step) and the remote delivery into
  THIS device's ``recv_buf[g % 2]``;
- capacity tokens: a landing slot is reused every 2 steps, and the reuse
  at step g is gated on the receiver's "read done" token from step g-2 —
  signaled only AFTER the receiver consumed the slot into its output.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpit_tpu.comm.collectives import _pvary

_LANE = 128
_SUBLANE = 8  # float32 tile rows


def _kernel(
    x_ref,
    o_ref,
    send_buf,
    recv_buf,
    send_sem,
    recv_sem,
    cap_sem,
    *,
    axis: str,
    num_devices: int,
    interpret: bool,
):
    p = num_devices
    i = lax.axis_index(axis)
    right = lax.rem(i + 1, p)
    left = lax.rem(i - 1 + p, p)
    rows = x_ref.shape[0] // p  # rows per chunk

    o_ref[...] = x_ref[...]

    if p == 1:
        return

    # Neighbor barrier: both neighbors must have entered the kernel (their
    # mailboxes and output buffers are live) before any remote write.
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left})
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right})
    pltpu.semaphore_wait(barrier, 2)

    total = 2 * (p - 1)  # continuous step counter across both phases

    def step(g, send_c, recv_c, *, accumulate):
        """One ring step: stage chunk ``send_c`` and ship it right; fold the
        chunk arriving from the left into output slot ``recv_c``."""
        # Back-pressure: the right neighbor's landing slot g%2 is reused
        # every 2 steps; wait for its "read done" token from step g-2
        # before writing into it again. Without this a fast sender runs
        # 2+ steps ahead and clobbers unconsumed data (two slots alone
        # are NOT a protocol).
        if g >= 2:
            pltpu.semaphore_wait(cap_sem.at[g % 2], 1)
        send_buf[...] = o_ref[pl.ds(send_c * rows, rows), :]
        rdma = pltpu.make_async_remote_copy(
            src_ref=send_buf,
            dst_ref=recv_buf.at[g % 2],
            send_sem=send_sem,
            recv_sem=recv_sem.at[g % 2],
            device_id={axis: right},
        )
        rdma.start()
        # Blocks on BOTH: my outgoing DMA finished reading send_buf (so the
        # next step may restage it) AND the left neighbor's chunk arrived
        # in recv_buf[g%2]. send_buf is never a remote-write target, so no
        # neighbor progress can corrupt a send in flight.
        rdma.wait()
        # _pvary feeds the interpret-mode VMA checker only; the real TPU
        # Mosaic lowering has no VMA tracking and rejects the primitive
        # (caught by the v5e-8 AOT compile check, utils/aot.py).
        incoming = recv_buf[g % 2]
        if interpret:
            incoming = _pvary(incoming, (axis,))
        if accumulate:
            o_ref[pl.ds(recv_c * rows, rows), :] += incoming
        else:
            o_ref[pl.ds(recv_c * rows, rows), :] = incoming
        # Landing slot consumed — only now may the left neighbor reuse it
        # (its step g+2).
        pltpu.semaphore_signal(cap_sem.at[g % 2], inc=1, device_id={axis: left})

    # Python loops, not fori_loop: p is static, and the step index must stay
    # a Python int so chunk indices are pure functions of the (device-
    # varying) axis_index — the interpreter's VMA checker rejects mixing a
    # replicated loop carry into varying address arithmetic.
    # ---- phase 1: reduce-scatter -----------------------------------------
    for s in range(p - 1):
        step(
            s,
            send_c=lax.rem(i - s + p, p),
            recv_c=lax.rem(i - s - 1 + 2 * p, p),
            accumulate=True,
        )

    # ---- phase 2: all-gather ---------------------------------------------
    # Device i now owns reduced chunk (i+1) mod p; circulate ownership.
    for s in range(p - 1):
        step(
            (p - 1) + s,
            send_c=lax.rem(i + 1 - s + 2 * p, p),
            recv_c=lax.rem(i - s + 2 * p, p),
            accumulate=False,
        )

    # Drain: the final two "read done" tokens (one per slot, from steps
    # total-1 and total-2) have no matching send-side wait; absorb them so
    # the semaphores return to zero for the next call.
    pltpu.semaphore_wait(cap_sem.at[(total - 1) % 2], 1)
    pltpu.semaphore_wait(cap_sem.at[(total - 2) % 2], 1)


def _ring_allreduce_2d(x2d, *, axis: str, interpret: bool):
    p = lax.axis_size(axis)
    kern = functools.partial(
        _kernel, axis=axis, num_devices=p, interpret=interpret
    )
    rows = x2d.shape[0] // p
    return pl.pallas_call(
        kern,
        # vma: the result is device-varying over the ring axis (shard_map
        # VMA checker requires kernels to declare this explicitly).
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype, vma=frozenset({axis})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((rows, _LANE), x2d.dtype),  # send staging (local-only)
            pltpu.VMEM((2, rows, _LANE), x2d.dtype),  # receive mailbox
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),  # per-slot capacity tokens
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=0
        ),
        # TPU interpret mode (not the generic pallas interpreter): simulates
        # remote DMAs + semaphores across shard_map "devices" on CPU.
        interpret=pltpu.InterpretParams() if interpret else False,
    )(x2d)


def ring_allreduce(x, axis: str, *, interpret: bool = False):
    """All-reduce-sum ``x`` over mesh axis ``axis`` — call inside shard_map.

    Accepts any shape/f32-or-bf16 dtype; the payload is raveled, padded to
    a [P · 8, 128] tile multiple, pushed through the Pallas ring, and
    restored. ``interpret=True`` runs the TPU interpret mode (works on the
    CPU fake mesh — the semaphore-discipline sanitizer of SURVEY.md §6).

    Equivalent to ``lax.psum(x, axis)``; exists as the native tier and for
    the GB/s benchmark. On non-TPU backends (where Mosaic can't lower the
    remote DMAs) the compiled path falls back to ``lax.psum`` — only
    ``interpret=True`` runs the actual ring protocol off-TPU.
    """
    if not interpret and jax.devices()[0].platform != "tpu":
        return lax.psum(x, axis)
    p = lax.axis_size(axis)
    if p == 1:
        # Degenerate ring: x already equals the sum. Entering the kernel
        # would deadlock — both phase loops are empty (no capacity tokens
        # ever signaled) while the drain waits on two of them.
        return x
    flat = jnp.ravel(x)
    n = flat.shape[0]
    sublane = 16 if x.dtype == jnp.bfloat16 else _SUBLANE
    pad = (-n) % (p * sublane * _LANE)
    flat = jnp.pad(flat, (0, pad))
    x2d = flat.reshape(-1, _LANE)
    out = _ring_allreduce_2d(x2d, axis=axis, interpret=interpret)
    return out.reshape(-1)[:n].reshape(x.shape)
