"""Corpus: memledger-seam fires exactly once — a marked allocation
seam that moves physical pages (here: slot free) without emitting a
memory-ledger event leaves the freed bytes attributed forever, and the
conservation invariant (grants − frees == held) breaks for every
capacity verdict downstream."""


# analysis: memledger-seam
def free_slot(alloc, slot):  # VIOLATION
    pages = alloc.slot_pages.pop(slot, ())
    released = 0
    for p in pages:
        alloc.refcount[p] -= 1
        if alloc.refcount[p] == 0:
            alloc.free.append(p)
            released += 1
    return released
