"""Corpus: determinism-seam fires exactly once — a wall-clock read in
a seeded-trace module silently makes every caller's trace a function
of the machine, not of (spec, seed)."""

# analysis: determinism-seam

import time


def generate_arrivals(spec, seed):
    jitter = time.time() % 1.0                # VIOLATION: wall clock
    return [spec.rate + jitter]
