"""Pallas ring allreduce — the native-tier ``MPI_Allreduce``.

The reference's allreduce hot path is ``mpiT.Allreduce`` → ``MPI_Allreduce``
→ libmpi's ring/tree (SURVEY.md §4.3). The XLA tier
(``comm.collectives.allreduce`` = ``lax.psum``) already lowers to an ICI
ring; this module is the hand-scheduled equivalent — the kernel the
"allreduce GB/s" benchmark measures and the in-tree proof that the
framework owns its communication stack down to the DMA level.

Algorithm (classic two-phase ring, bandwidth-optimal 2·(P-1)/P · N):

1. **Reduce-scatter** (P-1 steps): the payload is split into P chunks; at
   step s every device sends its running sum of chunk ``(i-s) mod P`` one
   hop clockwise through a double-buffered VMEM mailbox
   (``make_async_remote_copy``) and adds the chunk arriving from its left
   neighbor. After P-1 steps device i holds the fully-reduced chunk
   ``(i+1) mod P``.
2. **All-gather** (P-1 steps): the owned chunks circulate; each arriving
   chunk is written straight into its slot of the output — no mailbox
   needed, the output region IS the receive buffer.

Synchronization discipline (the part interpret-mode tests pin down):
- a neighbor barrier (``get_barrier_semaphore``) before the first send, so
  no device writes into a mailbox that is not yet live;
- per-slot DMA semaphores: ``rdma.wait()`` blocks on both the local send
  completion and the remote delivery into THIS device;
- alternating slots (s mod 2) so step s+1's incoming data can never
  clobber the slot step s is still reading.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128
_SUBLANE = 8  # float32 tile rows


def _vary(x, axis):
    # Scratch-buffer reads are VMA-replicated; retype to device-varying
    # before mixing with the (varying) output ref.
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis,), to="varying")
    return lax.pvary(x, (axis,))


def _kernel(
    x_ref, o_ref, comm_buf, send_sem, recv_sem, cap_sem, *, axis: str, num_devices: int
):
    p = num_devices
    i = lax.axis_index(axis)
    right = lax.rem(i + 1, p)
    left = lax.rem(i - 1 + p, p)
    rows = x_ref.shape[0] // p  # rows per chunk

    o_ref[...] = x_ref[...]

    if p == 1:
        return

    # Neighbor barrier: both neighbors must have entered the kernel (their
    # mailboxes and output buffers are live) before any remote write.
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left})
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right})
    pltpu.semaphore_wait(barrier, 2)

    def chunk(ref, c):
        return ref.at[pl.ds(c * rows, rows), :]

    total = 2 * (p - 1)  # continuous step counter across both phases

    def ship(g):
        """Step g: stage in slot g%2; the write lands in the RECEIVER's slot
        (g+1)%2 — distinct slots, so an early-arriving neighbor write never
        collides with this device's own staging."""
        # Back-pressure: before re-using a landing slot on the right
        # neighbor (every slot is re-used from step 2 on), wait for its
        # "slot free" signal — without this a fast sender can run 2+ steps
        # ahead and clobber unconsumed data (caught by the interpret-mode
        # tests; two slots alone are NOT a protocol).
        if g >= 2:
            pltpu.semaphore_wait(cap_sem.at[(g + 1) % 2], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[g % 2],
            dst_ref=comm_buf.at[(g + 1) % 2],
            send_sem=send_sem.at[g % 2],
            recv_sem=recv_sem.at[(g + 1) % 2],
            device_id={axis: right},
        )
        rdma.start()
        rdma.wait()  # my send done AND left neighbor's chunk delivered

    def consumed(g):
        """Tell the LEFT neighbor its landing slot on me is free again."""
        pltpu.semaphore_signal(
            cap_sem.at[(g + 1) % 2], inc=1, device_id={axis: left}
        )

    # Python loops, not fori_loop: p is static, and the step index must stay
    # a Python int so chunk indices are pure functions of the (device-
    # varying) axis_index — the interpreter's VMA checker rejects mixing a
    # replicated loop carry into varying address arithmetic.
    # ---- phase 1: reduce-scatter -----------------------------------------
    for s in range(p - 1):
        send_c = lax.rem(i - s + p, p)
        recv_c = lax.rem(i - s - 1 + 2 * p, p)
        # Stage the running sum of send_c into the mailbox, ship it right.
        comm_buf[s % 2] = o_ref[pl.ds(send_c * rows, rows), :]
        ship(s)
        o_ref[pl.ds(recv_c * rows, rows), :] += _vary(comm_buf[(s + 1) % 2], axis)
        consumed(s)

    # ---- phase 2: all-gather ---------------------------------------------
    # Device i now owns reduced chunk (i+1) mod p; circulate ownership.
    for s in range(p - 1):
        g = (p - 1) + s  # continuous step counter across phases
        send_c = lax.rem(i + 1 - s + 2 * p, p)
        recv_c = lax.rem(i - s + 2 * p, p)
        comm_buf[g % 2] = o_ref[pl.ds(send_c * rows, rows), :]
        ship(g)
        o_ref[pl.ds(recv_c * rows, rows), :] = _vary(comm_buf[(g + 1) % 2], axis)
        consumed(g)

    # Drain: the final two "slot free" signals have no matching send-side
    # wait; absorb them so the semaphores return to zero for the next call.
    pltpu.semaphore_wait(cap_sem.at[(total - 1) % 2], 1)
    pltpu.semaphore_wait(cap_sem.at[total % 2], 1)


def _ring_allreduce_2d(x2d, *, axis: str, interpret: bool):
    p = lax.axis_size(axis)
    kern = functools.partial(_kernel, axis=axis, num_devices=p)
    rows = x2d.shape[0] // p
    return pl.pallas_call(
        kern,
        # vma: the result is device-varying over the ring axis (shard_map
        # VMA checker requires kernels to declare this explicitly).
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype, vma=frozenset({axis})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, rows, _LANE), x2d.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),  # per-slot capacity tokens
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=0
        ),
        # TPU interpret mode (not the generic pallas interpreter): simulates
        # remote DMAs + semaphores across shard_map "devices" on CPU.
        interpret=pltpu.InterpretParams() if interpret else False,
    )(x2d)


def ring_allreduce(x, axis: str, *, interpret: bool = False):
    """All-reduce-sum ``x`` over mesh axis ``axis`` — call inside shard_map.

    Accepts any shape/f32-or-bf16 dtype; the payload is raveled, padded to
    a [P · 8, 128] tile multiple, pushed through the Pallas ring, and
    restored. ``interpret=True`` runs the TPU interpret mode (works on the
    CPU fake mesh — the semaphore-discipline sanitizer of SURVEY.md §6).

    Equivalent to ``lax.psum(x, axis)``; exists as the native tier and for
    the GB/s benchmark.
    """
    p = lax.axis_size(axis)
    flat = jnp.ravel(x)
    n = flat.shape[0]
    sublane = 16 if x.dtype == jnp.bfloat16 else _SUBLANE
    pad = (-n) % (p * sublane * _LANE)
    flat = jnp.pad(flat, (0, pad))
    x2d = flat.reshape(-1, _LANE)
    out = _ring_allreduce_2d(x2d, axis=axis, interpret=interpret)
    return out.reshape(-1)[:n].reshape(x.shape)
