"""Early pytest plugin: re-exec the test run onto a fake 8-device CPU mesh.

Loaded via ``pytest.ini`` ``addopts = -p reexec_cpu`` so it runs at plugin-
registration time — BEFORE pytest's fd-level capture starts — which keeps
the re-exec'd child's output on the real stdout. (``tests/conftest.py`` has
a fallback for runs that bypass pytest.ini, but by then capture has started
and the child's output is swallowed; this plugin is the primary path.)

Why re-exec at all: this environment's sitecustomize eagerly registers and
initializes the single-chip ``axon`` TPU backend in every Python process, so
in-process env changes are too late. The collective/sharding test suite
needs the fake 8-device CPU mesh (SURVEY.md §5.2) — the analogue of the
reference running MPI locally under ``mpirun -n 2..4`` (SURVEY.md §5.1).

Set ``MPIT_TEST_PLATFORM=axon`` to run on the real chip instead.
"""

import os
import sys

N_FAKE_DEVICES = 8


def cpu_mesh_env(n_devices: int = N_FAKE_DEVICES) -> dict:
    """A copy of ``os.environ`` rewritten for an ``n_devices`` fake CPU mesh.

    Strips ``PALLAS_AXON_POOL_IPS`` (the sitecustomize trigger that force-
    registers the single-chip axon backend and overrides ``JAX_PLATFORMS``)
    and forces the host-platform device count — replacing any pre-existing
    ``xla_force_host_platform_device_count`` flag, so a caller-supplied
    smaller count cannot survive into the child.
    """
    import re

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disables axon registration
    env["JAX_PLATFORMS"] = "cpu"
    xla_flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        env.get("XLA_FLAGS", ""),
    )
    xla_flags += f" --xla_force_host_platform_device_count={n_devices}"
    env["XLA_FLAGS"] = xla_flags.strip()
    return env


def reexec_onto_cpu_mesh_if_needed() -> None:
    if os.environ.get("MPIT_TEST_REEXEC") == "1":
        return
    if os.environ.get("MPIT_TEST_PLATFORM", "cpu") != "cpu":
        return
    # Honor a caller-supplied device count (e.g. XLA_FLAGS=...=16 pytest)
    # rather than forcing N_FAKE_DEVICES over it.
    import re

    m = re.search(
        r"--xla_force_host_platform_device_count=(\d+)",
        os.environ.get("XLA_FLAGS", ""),
    )
    env = cpu_mesh_env(int(m.group(1)) if m else N_FAKE_DEVICES)
    env["MPIT_TEST_REEXEC"] = "1"
    sys.stdout.flush()
    sys.stderr.flush()
    os.execve(sys.executable, [sys.executable, "-m", "pytest", *sys.argv[1:]], env)


# Auto-run only when this module is being loaded by pytest itself (the
# ``-p reexec_cpu`` early-plugin path, or a conftest import during startup).
# Plain consumers of :func:`cpu_mesh_env` (e.g. ``__graft_entry__``) must be
# able to import this module without being exec'd into a pytest run.
if "_pytest.config" in sys.modules:
    reexec_onto_cpu_mesh_if_needed()
