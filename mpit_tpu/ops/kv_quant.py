"""Quantized int8 KV-cache storage: the wire format + its one math.

ISSUE 15 tentpole. PR 8's length-aware roofline recorded the decode
verdict — ``bound_modeled: hbm``, every tick dominated by sweeping the
visited K/V tiles out of HBM — and capacity is bounded by bytes per
cached token. This module is the storage half of the fix: K/V rows are
stored as **int8 + per-(row, head) f32 scales** and dequantized per
visited tile inside the decode kernel, so what crosses HBM→VMEM is the
int8 tiles plus their scale blocks (~2× fewer bytes than bf16, ~4× vs
f32), and the same HBM pool holds ~2× the tokens.

The quantization math is NOT new: it is the EQuARX-style (arXiv
2506.17615) ``amax/127`` round-half-to-even recipe the ring collectives
shipped in PR 9, reached through the SAME
:func:`mpit_tpu.ops.ring_collectives.quantize_blocks` /
:func:`~mpit_tpu.ops.ring_collectives.dequantize_blocks` helpers — one
rounding contract repo-wide, so the collectives' determinism and
round-trip-bound pins govern the cache too.

Grain: one scale per **(token row, head)** — for a paged pool the scale
block of page ``p``, head ``h`` is the ``[page_size]`` tile
``scale[p, :, h]``, which is what rides next to the page through
admission, copy-on-write, prefix sharing and preemption (the allocator
never learns about scales: they live in the same pytree as the int8
buffer and every page copy / table indirection applies to both).
Per-row grain is what makes append-only writes exact: a row is
quantized once, when written, and never rescaled by a later append.

:class:`QuantizedKV` is the container: a registered pytree ``(q int8,
scale f32)`` that drops into every ``KVCache.k`` / ``PagedKVCache.k``
seat. The scale keeps a trailing size-1 axis (``[..., H, 1]`` vs the
buffer's ``[..., H, Dh]``) so both leaves share rank and the engine's
slot-select masks broadcast over either through one ``tree.map``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from mpit_tpu.ops.ring_collectives import (
    dequantize_blocks,
    quantize_blocks,
)

__all__ = [
    "QuantizedKV",
    "quantize_kv",
    "dequantize_kv",
    "kv_stack",
    "kv_wire_bytes_per_row",
]

# f32 scale per (row, head): the storage grain's fixed overhead.
SCALE_BYTES = 4


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedKV:
    """One quantized K (or V) buffer: ``q`` int8 ``[..., H, Dh]`` plus
    ``scale`` f32 ``[..., H, 1]`` (keepdims — equal rank, so masks and
    shardings written for the buffer broadcast/apply to both leaves).
    A pytree: it passes through jit/shard_map/device_put whole, and
    ``jax.tree.map`` over a cache touches q and scale together."""

    q: Any
    scale: Any

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    # Shape/dtype delegate to the int8 payload — callers sizing slots/
    # pages/rows read the buffer geometry; the wire dtype IS int8.
    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    def __getitem__(self, idx):
        """Index q and scale together (the per-layer ``cache.k[i]``
        view the blocks consume)."""
        return QuantizedKV(q=self.q[idx], scale=self.scale[idx])


def quantize_kv(x):
    """Quantize K/V rows ``[..., H, Dh]`` at the per-(row, head) grain:
    one scale per trailing ``Dh`` slice, via the shared
    :func:`~mpit_tpu.ops.ring_collectives.quantize_blocks` contract."""
    q, scale = quantize_blocks(x, axis=-1)
    return QuantizedKV(q=q, scale=scale)


def dequantize_kv(kv: QuantizedKV):
    """f32 view of a quantized buffer (the reference/oracle path; the
    flash-decode kernel never calls this on a whole buffer — it
    dequantizes per visited tile in VMEM)."""
    return dequantize_blocks(kv.q, kv.scale)


def kv_stack(buffers):
    """``jnp.stack`` over a list of per-layer cache buffers, plain
    arrays or :class:`QuantizedKV` alike (tree-mapped, so q and scale
    stack together)."""
    return jax.tree.map(lambda *ls: jnp.stack(ls), *buffers)


def kv_wire_bytes_per_row(num_heads: int, head_dim: int, dtype) -> float:
    """HBM bytes ONE cached K (or V) row actually occupies on the wire
    — the unit of the length-aware decode-bytes model and the capacity
    math (ISSUE 15 roofline-honesty satellite). ``dtype`` "int8" (or
    the int8 numpy dtype) = int8 payload + one f32 scale per head;
    anything else = the dense row in that dtype."""
    if dtype == "int8" or jnp.dtype(dtype) == jnp.int8:
        return float(num_heads * (head_dim + SCALE_BYTES))
    return float(num_heads * head_dim * jnp.dtype(dtype).itemsize)
