"""Elastic asynchronous EASGD multi-replica tier (ISSUE 11 tentpole).

The reference's defining capability — a pserver/pclient fleet that keeps
training through slow and dying workers (Zhang–Choromanska–LeCun EASGD,
arXiv:1412.6651; the MXNET-MPI task-model embedding, arXiv:1801.03855) —
re-grown on this repo's own layers: N data-parallel replicas each run the
production async :func:`~mpit_tpu.train.loop.hardened_loop` and exchange
an elastic-averaging anchor

    replica:  x_i ← x_i − α·(x_i − x̃)
    anchor:   x̃  ← x̃ + α·(x_i − x̃)

with an **anchor server** actor (grown from ``asyncsgd/actors.py``'s
pserver loop) over the :mod:`mpit_tpu.compat` layer. Design points:

- **Dedicated channel.** All anchor traffic rides a ``Comm_dup`` of the
  world communicator (key ``"elastic-anchor"``) — its own matching
  space, so an application's outstanding wildcard receives can never
  steal anchor messages (the PR-3 flight-recorder discipline).
- **Bounded-staleness, per-replica pulls.** Each replica exchanges with
  the anchor every ``sync_every`` of *its own* steps; the server is
  asynchronous, so a straggler delays only its own anchor exchange,
  never the fleet. The server tracks per-replica anchor-version
  staleness (gauged; past ``staleness_bound`` → an
  ``anchor_staleness_exceeded`` instant + sentinel note).
- **Heartbeat + lease liveness.** Each replica runs a heartbeat thread
  on the anchor channel; the server's probe loop (built on the compat
  ``timeout=`` satellite) sweeps leases between messages. A silent
  replica is **evicted** — removed from the averaging denominator
  (``α = β / N_active`` when ``beta > 0``: graceful N→N−1 degradation)
  with a ``replica_evicted`` instant; a replica heard from again (a
  bounded hang, a rejoin after crash-restore) is re-admitted with a
  ``replica_rejoined`` instant.
- **Crash / rejoin.** A replica killed mid-run (``FaultPlan.kill_at`` →
  :class:`~mpit_tpu.compat.faults.ReplicaKilled`) stops heartbeating,
  gets evicted, then restores from its latest crash-consistent
  :class:`~mpit_tpu.train.checkpoint.AtomicCheckpoint`, re-registers
  over ``TAG_REJOIN``, pulls the current anchor, and resumes its
  ``hardened_loop`` for the remaining steps.
- **DivergenceGuard quarantine.** Before every push the replica checks
  its flat params for finiteness: a diverged replica sends
  ``TAG_QUAR`` (the server drops it from the denominator) instead of
  poisoning the anchor, then ``hardened_loop``'s existing guard +
  older-checkpoint restore machinery rolls it back, and the restore
  event triggers an anchor rejoin + center pull.

The replica's training state is a :class:`~mpit_tpu.train.step.TrainState`
whose ``params`` leaf is the **flat float32 parameter vector** (the
pserver protocol's canonical layout, as in the parity actors); the
jitted local step is supplied by the caller and shared across replicas
(one compile serves the fleet).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
from typing import Any, Callable, Iterator

import numpy as np

from mpit_tpu import compat as mpiT
from mpit_tpu.compat.faults import FaultPlan, ReplicaKilled
from mpit_tpu.obs import core as _obs
from mpit_tpu.train.checkpoint import AtomicCheckpoint
from mpit_tpu.train.loop import hardened_loop
from mpit_tpu.train.metrics import MetricLogger

ANCHOR_CHANNEL = "elastic-anchor"
SERVER_RANK = 0

# Anchor protocol tags (disjoint from the asyncsgd actors' 11..15 range,
# though the dedicated Comm_dup already isolates the matching space).
TAG_REG = 31
TAG_HB = 32
TAG_EXCH = 33
TAG_CENTER = 34
TAG_QUAR = 35
TAG_REJOIN = 36
TAG_STOP = 37

_TAG_NAMES = {TAG_REG: "register", TAG_HB: "heartbeat", TAG_EXCH: "exchange",
              TAG_CENTER: "center", TAG_QUAR: "quarantine",
              TAG_REJOIN: "rejoin", TAG_STOP: "stop"}


@dataclasses.dataclass
class ElasticConfig:
    """Knobs of the elastic tier (CLI surface: ``asyncsgd`` flags).

    ``alpha`` is the per-exchange elastic coupling; when ``beta > 0``
    the server instead derives ``alpha = beta / N_active`` from the live
    replica count (the paper's β = N·α stability spelling — eviction
    then *strengthens* each survivor's coupling, the graceful N→N−1
    denominator change). ``lease_s`` must comfortably exceed
    ``heartbeat_s`` (the server warns when it doesn't).
    """

    replicas: int = 2
    steps: int = 60  # per-replica local steps
    sync_every: int = 4
    alpha: float = 0.125
    beta: float = 0.0
    staleness_bound: int = 8
    heartbeat_s: float = 0.05
    lease_s: float = 0.5
    exchange_timeout_s: float = 10.0
    exchange_retries: int = 3
    backoff: float = 1.5
    ckpt_dir: str = ""
    ckpt_every: int = 0
    max_restores: int = 2
    max_to_keep: int = 3
    log_every: int = 10
    fetch_lag: int = 2
    rejoin: bool = True  # a killed replica rejoins from its checkpoint


class AnchorTimeoutError(RuntimeError):
    """The anchor server stayed silent through every retry/backoff round
    of one client call — the replica's view of a dead anchor."""


# ---------------------------------------------------------------------------
# Server actor.
# ---------------------------------------------------------------------------


class _ReplicaSlot:
    __slots__ = ("last_hb", "active", "quarantined", "stopped")

    def __init__(self, now: float):
        self.last_hb = now
        self.active = True
        self.quarantined = False
        self.stopped = False


def anchor_server(
    init_flat: np.ndarray,
    cfg: ElasticConfig,
    *,
    nreplicas: int | None = None,
    comm=None,
    sentinel=None,
) -> dict:
    """The anchor actor: rank 0 of the elastic job.

    Serves register/exchange/rejoin/stop on the anchor channel until
    every replica sent ``TAG_STOP``; sweeps heartbeat leases between
    messages (the probe timeout **is** the liveness clock — no separate
    timer thread). Returns the final center, version, and the lifecycle
    event log (``registered`` / ``evicted`` / ``rejoined`` /
    ``quarantined`` / ``staleness_exceeded`` / ``stopped`` tuples) the
    tests and bench read.
    """
    nreplicas = cfg.replicas if nreplicas is None else nreplicas
    if cfg.lease_s < 2 * cfg.heartbeat_s:
        import warnings

        warnings.warn(
            f"elastic: lease_s={cfg.lease_s} < 2x heartbeat_s="
            f"{cfg.heartbeat_s} — healthy replicas will flap eviction",
            stacklevel=2,
        )
    ship = mpiT.Comm_dup(comm, key=ANCHOR_CHANNEL)
    center = np.array(init_flat, np.float32, copy=True)
    flat_buf = np.empty((center.size + 1,), np.float32)  # [version_seen, *x]
    ctrl_buf = np.empty((1,), np.int32)
    version = 0
    slots: dict[int, _ReplicaSlot] = {}
    events: list[tuple] = []
    stops = 0
    probe_timeout = max(min(cfg.lease_s / 4.0, cfg.heartbeat_s), 0.005)

    def _active_count() -> int:
        return sum(
            1 for s in slots.values()
            if s.active and not s.quarantined and not s.stopped
        )

    def _alpha() -> float:
        if cfg.beta > 0.0:
            return cfg.beta / max(1, _active_count())
        return cfg.alpha

    _INSTANT_NAMES = {
        "evicted": "replica_evicted",
        "rejoined": "replica_rejoined",
        "quarantined": "replica_quarantined",
        "staleness_exceeded": "anchor_staleness_exceeded",
    }

    def _note(kind: str, rank: int, **extra):
        events.append((kind, rank, *extra.values()))
        _obs.instant(_INSTANT_NAMES.get(kind, kind), rank=rank, **extra)
        if sentinel is not None and kind in (
            "evicted", "staleness_exceeded"
        ):
            # Sentinel rule (ISSUE 11 obs wiring): liveness and
            # staleness breaches land in the run's one anomaly verdict
            # next to spike/sustained findings; ``clean`` goes false.
            sentinel.note(kind, "anchor", version, rank=rank, **extra)

    def _gauges():
        _obs.gauge("active_replicas", _active_count())
        _obs.gauge("anchor_version", version)

    def _readmit(rank: int, how: str):
        s = slots[rank]
        if not s.active or s.quarantined:
            s.active = True
            s.quarantined = False
            _note("rejoined", rank, how=how)
            _gauges()

    def _sweep(now: float):
        for rank, s in slots.items():
            if s.stopped:
                continue
            age = now - s.last_hb
            _obs.gauge("replica_heartbeat_age_s", round(age, 4), rank=rank)
            if s.active and age > cfg.lease_s:
                s.active = False
                _note("evicted", rank, heartbeat_age_s=round(age, 4))
                _gauges()

    def _reply_center(rank: int):
        # [version, alpha, *center] — one payload, one Send; the client
        # applies the SAME alpha the server will use, keeping the pull
        # symmetric (the paper's coupled update).
        mpiT.Send(
            np.concatenate(
                [np.asarray([version, _alpha()], np.float32), center]
            ),
            dest=rank, tag=TAG_CENTER, comm=ship,
        )

    while stops < nreplicas:
        try:
            with _obs.span("anchor:probe_wait"):
                st = mpiT.Probe(
                    mpiT.ANY_SOURCE, mpiT.ANY_TAG, comm=ship,
                    timeout=probe_timeout,
                )
        except mpiT.CompatTimeoutError:
            _sweep(time.monotonic())
            continue
        now = time.monotonic()
        _obs.counter(
            "anchor_msgs", 1, kind=_TAG_NAMES.get(st.tag, str(st.tag))
        )
        if st.tag in (TAG_REG, TAG_REJOIN):
            mpiT.Recv(ctrl_buf, src=st.source, tag=st.tag, comm=ship)
            if st.source not in slots:
                slots[st.source] = _ReplicaSlot(now)
                events.append(("registered", st.source))
            else:
                slots[st.source].last_hb = now
                slots[st.source].stopped = False
                _readmit(st.source, how="rejoin")
            _gauges()
            _reply_center(st.source)
        elif st.tag == TAG_HB:
            mpiT.Recv(ctrl_buf, src=st.source, tag=TAG_HB, comm=ship)
            s = slots.get(st.source)
            if s is not None and not s.stopped:
                s.last_hb = now
                # A heartbeat from an evicted-but-alive replica (a
                # bounded hang outlived its lease): readmit — the
                # replica never knew it was gone. Quarantined replicas
                # stay out until their explicit rejoin.
                if not s.active and not s.quarantined:
                    _readmit(st.source, how="heartbeat")
        elif st.tag == TAG_EXCH:
            mpiT.Recv(flat_buf, src=st.source, tag=TAG_EXCH, comm=ship)
            s = slots.get(st.source)
            if s is None:
                s = slots[st.source] = _ReplicaSlot(now)
                events.append(("registered", st.source))
            s.last_hb = now
            if not s.active and not s.quarantined:
                _readmit(st.source, how="exchange")
            # Per-replica anchor staleness: how many center updates this
            # replica missed since its last pull. A straggler's gauge
            # climbs; past the bound it is an instant + sentinel note —
            # measured, not fatal (bounded staleness IS the design).
            staleness = version - int(flat_buf[0])
            _obs.gauge("replica_staleness", staleness, rank=st.source)
            if staleness > cfg.staleness_bound:
                _note(
                    "staleness_exceeded", st.source, staleness=staleness,
                    bound=cfg.staleness_bound,
                )
            a = _alpha()
            _reply_center(st.source)
            with _obs.span("anchor:update"):
                x_i = flat_buf[1:]
                center += np.float32(a) * (x_i - center)
            version += 1
            _gauges()
        elif st.tag == TAG_QUAR:
            mpiT.Recv(ctrl_buf, src=st.source, tag=TAG_QUAR, comm=ship)
            s = slots.get(st.source)
            if s is not None:
                s.quarantined = True
                s.last_hb = now
                _note("quarantined", st.source, step=int(ctrl_buf[0]))
                _gauges()
        elif st.tag == TAG_STOP:
            mpiT.Recv(ctrl_buf, src=st.source, tag=TAG_STOP, comm=ship)
            s = slots.get(st.source)
            if s is not None:
                s.stopped = True
            events.append(("stopped", st.source))
            stops += 1
            _gauges()
        else:  # consume to avoid deadlock, then fail loudly (pserver rule)
            mpiT.Recv(
                np.empty((st.count,), np.float32),
                src=st.source, tag=st.tag, comm=ship,
            )
            raise RuntimeError(
                f"anchor_server: unexpected tag {st.tag} from {st.source}"
            )
        _sweep(time.monotonic())
    return {
        "center": center,
        "version": version,
        "alpha_final": _alpha(),
        "events": events,
        "evictions": sum(1 for e in events if e[0] == "evicted"),
        "rejoins": sum(1 for e in events if e[0] == "rejoined"),
        "quarantines": sum(1 for e in events if e[0] == "quarantined"),
    }


# ---------------------------------------------------------------------------
# Client proxy (linked into each replica's training loop).
# ---------------------------------------------------------------------------


class AnchorClient:
    """A replica's anchor proxy: register / exchange / quarantine /
    rejoin / stop, plus the heartbeat thread.

    Every server round trip posts the reply receive BEFORE sending the
    request (the reference's Irecv/Isend overlap shape) and waits with
    the compat ``timeout=`` under retry/backoff — a dead anchor is an
    :class:`AnchorTimeoutError` naming the call, never a silent hang.
    """

    def __init__(self, flat_dim: int, cfg: ElasticConfig, *, comm=None):
        self._cfg = cfg
        self._ship = mpiT.Comm_dup(comm, key=ANCHOR_CHANNEL)
        self._rank = mpiT.Comm_rank(mpiT.COMM_WORLD)
        self._buf = np.empty((flat_dim + 2,), np.float32)  # [ver, alpha, *x̃]
        self.version = 0
        self.alpha = cfg.alpha
        self._hb_stop: threading.Event | None = None
        self._hb_suspend_until = 0.0
        self._step = 0

    # -- plumbing ------------------------------------------------------------
    def _rpc(self, tag: int, payload: np.ndarray, what: str) -> np.ndarray:
        req = mpiT.Irecv(
            self._buf, src=SERVER_RANK, tag=TAG_CENTER, comm=self._ship
        )
        mpiT.Isend(payload, dest=SERVER_RANK, tag=tag, comm=self._ship)
        t = self._cfg.exchange_timeout_s
        for attempt in range(self._cfg.exchange_retries + 1):
            try:
                with _obs.span(f"anchor:{what}", attempt=attempt):
                    mpiT.Wait(req, timeout=t)
                break
            except mpiT.CompatTimeoutError:
                # The request stays posted — retry the WAIT (never the
                # send: a duplicate TAG_EXCH would double-update the
                # center) with a grown window.
                _obs.counter("anchor_retries", 1, rank=self._rank)
                if attempt >= self._cfg.exchange_retries:
                    raise AnchorTimeoutError(
                        f"anchor {what} on rank {self._rank}: no reply "
                        f"after {attempt + 1} waits (last {t:.3g}s)"
                    ) from None
                t *= self._cfg.backoff
        self.version = int(self._buf[0])
        self.alpha = float(self._buf[1])
        return self._buf[2:]

    # -- lifecycle -----------------------------------------------------------
    def register(self, step: int = 0) -> np.ndarray:
        self._step = step
        return self._rpc(
            TAG_REG, np.asarray([step], np.int32), "register"
        ).copy()

    def rejoin(self, step: int) -> np.ndarray:
        """Re-register after a crash-restore (or quarantine-restore) and
        pull the CURRENT anchor; returns the center (apply the elastic
        pull to the restored params before training on)."""
        self._step = step
        return self._rpc(
            TAG_REJOIN, np.asarray([step], np.int32), "rejoin"
        ).copy()

    def exchange(self, flat: np.ndarray, step: int) -> np.ndarray:
        """One EASGD round trip: push ``x_i`` (stamped with the last
        anchor version this replica saw — the server's staleness
        input), pull the center, return the elastically-pulled params."""
        self._step = step
        payload = np.concatenate(
            [np.asarray([self.version], np.float32),
             np.asarray(flat, np.float32)]
        )
        center = self._rpc(TAG_EXCH, payload, "exchange")
        return flat - np.float32(self.alpha) * (flat - center)

    def quarantine(self, step: int) -> None:
        """Tell the anchor this replica's params are poisoned: it stops
        counting toward the denominator and nothing is pushed."""
        mpiT.Isend(
            np.asarray([step], np.int32), dest=SERVER_RANK, tag=TAG_QUAR,
            comm=self._ship,
        )

    def stop(self, step: int) -> None:
        self.stop_heartbeats()
        mpiT.Isend(
            np.asarray([step], np.int32), dest=SERVER_RANK, tag=TAG_STOP,
            comm=self._ship,
        )

    # -- heartbeats ----------------------------------------------------------
    def start_heartbeats(self) -> None:
        if self._hb_stop is not None:
            return
        stop = self._hb_stop = threading.Event()
        rank, ship, cfg = self._rank, self._ship, self._cfg
        # The replica thread's (possibly per-rank) recorder: heartbeat
        # sends must be charged to THIS rank's event stream, or the
        # flight recorder's gathered send matrix disagrees with the
        # server's receive counts by exactly the heartbeat traffic.
        rank_rec = _obs.get_recorder()

        def _beat():
            # The helper thread adopts the replica's rank identity so
            # its sends carry the right source (compat.bind_thread) AND
            # the replica's recorder so they are attributed to it.
            mpiT.bind_thread(rank, ship)
            rec_ctx = (
                _obs.local_recorder(rank_rec) if rank_rec is not None
                else contextlib.nullcontext()
            )
            with rec_ctx:
                while not stop.wait(cfg.heartbeat_s):
                    if time.monotonic() < self._hb_suspend_until:
                        continue  # a simulated full-process stall
                    mpiT.Send(
                        np.asarray([self._step], np.int32),
                        dest=SERVER_RANK, tag=TAG_HB, comm=ship,
                    )

        t = threading.Thread(
            target=_beat, daemon=True, name=f"elastic-hb-{rank}"
        )
        t.start()

    def suspend_heartbeats(self, seconds: float) -> None:
        """Model a full-process stall (``FaultPlan.hang_at``): compute
        AND heartbeats stop, so the lease can expire."""
        self._hb_suspend_until = time.monotonic() + seconds

    def stop_heartbeats(self) -> None:
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_stop = None


# ---------------------------------------------------------------------------
# Replica runner: hardened_loop + anchor exchange + fault application.
# ---------------------------------------------------------------------------


class _RestoreHookLogger:
    """MetricLogger wrapper watching for ``hardened_loop``'s
    ``restored_after_divergence`` event — the seam through which a
    divergence restore triggers the anchor rejoin (the loop owns the
    restore; the elastic tier only needs to know it happened).

    Also accumulates every logged loss into ``losses``: a crashed
    ``hardened_loop`` invocation never returns its result, so the
    replica's loss trajectory must be collected at the logging seam or
    the pre-crash segment silently vanishes (a replica killed before
    its first checkpoint would report ``final_loss=nan`` despite
    training). Log-point cadence, like the loop's own trace; entries
    logged on a later-abandoned (pre-rollback) segment stay in the
    list — it is a diagnostic trajectory, not a resume input."""

    def __init__(self, inner: MetricLogger, hook: Callable[[int], None]):
        self._inner = inner
        self._hook = hook
        self.losses: list[float] = []

    def log(self, step: int, metrics: dict) -> None:
        if metrics.get("event") == "restored_after_divergence":
            self._hook(int(step))
        loss = metrics.get("loss")
        if isinstance(loss, (int, float)):
            self.losses.append(float(loss))
        self._inner.log(step, metrics)


def _replica_body(
    rank: int,
    ridx: int,
    world,
    cfg: ElasticConfig,
    init_state: Callable[[], Any],
    step_fn: Callable,
    stream_factory: Callable[[int, int], Iterator],
    plan: FaultPlan | None,
    items_per_batch: int | None,
    verbose: bool,
) -> dict:
    import jax.numpy as jnp

    state0 = init_state()
    flat_dim = int(np.asarray(state0.params).size)
    client = AnchorClient(flat_dim, cfg)
    client.register(0)
    client.start_heartbeats()
    ckpt = (
        AtomicCheckpoint(
            os.path.join(cfg.ckpt_dir, f"replica{ridx}"),
            max_to_keep=cfg.max_to_keep,
        )
        if cfg.ckpt_dir
        else None
    )
    stats = {
        "replica": ridx, "restores": 0, "rejoins": 0, "quarantines": 0,
        "crashes": 0, "exchanges": 0,
    }
    # Host-side step cursor + cross-call flags shared between the
    # wrapped step, the restore hook, and the crash supervisor. The
    # cursor (not ``int(state.step)``) keys fault application and sync
    # cadence so the async pipeline never pays a per-step device fetch.
    cell: dict[str, Any] = {"k": 0, "quarantined": False, "pending_center": None}

    def _on_restore(restored_step: int) -> None:
        # hardened_loop just restored this replica from its checkpoint
        # (DivergenceGuard). Re-sync the cursor, then rejoin the anchor:
        # pull the current center and stage the elastic pull for the
        # next wrapped call (the hook cannot mutate the loop's state).
        cell["k"] = restored_step
        stats["restores"] += 1
        center = client.rejoin(restored_step)
        cell["pending_center"] = (center, client.alpha)
        cell["quarantined"] = False
        stats["rejoins"] += 1

    def wrapped(state, batch):
        k = cell["k"]
        pc = cell.pop("pending_center", None)
        if pc is not None:
            center, alpha = pc
            flat = np.asarray(state.params, np.float32)
            state = state._replace(
                params=jnp.asarray(flat - np.float32(alpha) * (flat - center))
            )
        if plan is not None:
            act = plan.step_action(rank, k)  # may raise ReplicaKilled
            if act.hang_s:
                # Full-process stall: heartbeats stop too — the lease
                # expires, the anchor evicts, and the resumed heartbeat
                # re-admits (the hang→evict→readmit path).
                client.suspend_heartbeats(act.hang_s)
                time.sleep(act.hang_s)
            elif act.sleep_s:
                time.sleep(act.sleep_s)
        state, metrics = step_fn(state, batch)
        if plan is not None and act.nan:
            # Poison the step's params: the NEXT loss is non-finite, the
            # guard raises at its fence, and the quarantine check below
            # keeps the poison out of the anchor meanwhile.
            state = state._replace(
                params=state.params * jnp.float32(float("nan"))
            )
        k += 1
        cell["k"] = k
        client._step = k
        if k % cfg.sync_every == 0:
            flat = np.asarray(state.params, np.float32)
            if not np.all(np.isfinite(flat)):
                # DivergenceGuard quarantine: a diverged replica must
                # never push — one poisoned x_i would NaN the center
                # for the whole fleet.
                if not cell["quarantined"]:
                    cell["quarantined"] = True
                    stats["quarantines"] += 1
                    client.quarantine(k)
                    _obs.instant("replica_diverged_local", rank=rank, step=k)
            elif not cell["quarantined"]:
                with _obs.span("elastic_exchange", step=k):
                    pulled = client.exchange(flat, k)
                stats["exchanges"] += 1
                state = state._replace(params=jnp.asarray(pulled))
        return state, metrics

    logger = _RestoreHookLogger(MetricLogger(stdout=verbose), _on_restore)
    transform = lambda b: {k: jnp.asarray(v) for k, v in b.items()}  # noqa: E731
    result = None
    state = state0
    start_step = 0
    if ckpt is not None and ckpt.latest_step() is not None:
        # A relaunched fleet (the whole process crashed and was
        # restarted — the chaos e2e path) resumes each replica from its
        # latest crash-consistent checkpoint; the anchor center is soft
        # state, rebuilt from the replicas' first exchanges.
        state = ckpt.restore(state0)
        start_step = int(state.step)
        cell["k"] = start_step
        stats["resumed_from"] = start_step
    t0 = time.perf_counter()
    try:
        while True:
            try:
                result = hardened_loop(
                    world,
                    state,
                    wrapped,
                    stream_factory(ridx, start_step),
                    steps=cfg.steps,
                    transform=transform,
                    items_per_batch=items_per_batch,
                    log_every=cfg.log_every,
                    logger=logger,
                    ckpt=ckpt,
                    ckpt_every=cfg.ckpt_every if ckpt else 0,
                    specs=(lambda: None) if ckpt else None,
                    max_restores=cfg.max_restores,
                    fetch_lag=cfg.fetch_lag,
                )
                break
            except ReplicaKilled as rk:
                # Crash: the thread's heart stops; the anchor evicts on
                # lease expiry. Rejoin = restore the latest
                # crash-consistent checkpoint, re-register, pull the
                # anchor, resume the loop for the remaining steps.
                stats["crashes"] += 1
                client.stop_heartbeats()
                _obs.instant("replica_crashed", rank=rank, step=rk.step)
                if not cfg.rejoin or ckpt is None or ckpt.latest_step() is None:
                    stats["dead_at"] = rk.step
                    break
                if plan is not None and plan.rejoin_delay_s > 0:
                    time.sleep(plan.rejoin_delay_s)
                state = ckpt.restore(state0)
                start_step = int(state.step)
                stats["rejoin_steps_to_recover"] = rk.step - start_step
                center = client.rejoin(start_step)
                cell["pending_center"] = (center, client.alpha)
                cell["k"] = start_step
                cell["quarantined"] = False
                stats["rejoins"] += 1
                client.start_heartbeats()
    finally:
        client.stop(cell["k"])
    wall = time.perf_counter() - t0
    steps_done = int(result["steps"]) if result else cell["k"]
    # The trajectory comes from the logging seam, not the loop result:
    # a crashed segment's losses would otherwise vanish with the
    # never-returned result (see _RestoreHookLogger).
    losses = logger.losses
    out = {
        **stats,
        "steps": steps_done,
        "losses": losses,
        "final_loss": losses[-1] if losses else float("nan"),
        "wall_s": round(wall, 3),
        "steps_per_s": round(steps_done / wall, 3) if wall > 0 else 0.0,
        "completed": result is not None,
    }
    if result:
        out["loop_restores"] = result["restores"]
        for key in ("items_per_sec", "items_per_sec_last", "items_per_sec_mean"):
            if key in result:
                out[key] = result[key]
    return out


# ---------------------------------------------------------------------------
# Fleet launcher.
# ---------------------------------------------------------------------------


def run_elastic(
    world,
    cfg: ElasticConfig,
    init_state: Callable[[], Any],
    step_fn: Callable,
    stream_factory: Callable[[int, int], Iterator],
    *,
    fault_plan: FaultPlan | None = None,
    sentinel=None,
    items_per_batch: int | None = None,
    job_timeout_s: float = 600.0,
    flight: bool = True,
    verbose: bool = False,
) -> dict:
    """Launch the elastic fleet: 1 anchor server + ``cfg.replicas``
    replicas on the compat layer (the ``mpirun -n P`` shape).

    Args:
      world: the jax World (prefetch plumbing only — replicas place
        whole batches; no SPMD sharding inside a replica).
      init_state: ``() -> TrainState`` with ``params`` = the flat f32
        vector (fresh per replica; all replicas start from the same
        init, which also seeds the anchor center).
      step_fn: the SHARED jitted local step ``(state, batch) -> (state,
        metrics)`` — one compile serves every replica.
      stream_factory: ``(replica_idx, skip) -> batch iterator`` (skip =
        steps already trained, for the rejoin resume).
      fault_plan: seeded :class:`~mpit_tpu.compat.faults.FaultPlan` —
        message faults install on the job's wire; step faults apply in
        the replica wrapper.
      flight: record per-rank telemetry (``obs.local_recorder`` per
        rank) and gather it to the server at end of job — the result's
        ``flight`` block carries the per-phase skew report naming any
        straggler (PR 3's flight recorder, exercised on real threads).
      sentinel: optional :class:`mpit_tpu.obs.Sentinel` — the server
        notes evictions/staleness breaches into it.

    Returns ``{"server": {...}, "replicas": [...], "center", "version",
    "flight": {...}, "fault_events": (...)}``.
    """
    from mpit_tpu.obs import aggregate

    nranks = cfg.replicas + 1
    state0 = init_state()
    init_flat = np.asarray(state0.params, np.float32).copy()
    del state0

    def main(rank: int):
        rec_ctx = (
            _obs.local_recorder(_obs.Recorder()) if flight
            else contextlib.nullcontext()
        )
        with rec_ctx:
            if rank == SERVER_RANK:
                out = anchor_server(init_flat, cfg, sentinel=sentinel)
            else:
                out = _replica_body(
                    rank, rank - 1, world, cfg, init_state, step_fn,
                    stream_factory, fault_plan, items_per_batch, verbose,
                )
            per_rank = aggregate.gather_compat(root=SERVER_RANK) if flight else None
        if rank == SERVER_RANK and per_rank is not None:
            out["_flight"] = {
                "skew": aggregate.skew_report(per_rank),
                "record": aggregate.flight_record(per_rank),
            }
        return out

    results = mpiT.run(
        main, nranks, pass_rank=True, timeout=job_timeout_s,
        fault_plan=fault_plan,
    )
    server = results[SERVER_RANK]
    flight_doc = server.pop("_flight", None)
    out = {
        "server": server,
        "replicas": results[1:],
        "center": server["center"],
        "version": server["version"],
    }
    if flight_doc is not None:
        # The headline question ("which replica straggled?") reads the
        # TRAINING step phase — the record's global max-skew phase is
        # usually the server's probe_wait (it idles by design).
        step_skew = flight_doc["skew"].get("step")
        if step_skew is not None:
            flight_doc["step_straggler_rank"] = step_skew["max_rank"]
        out["flight"] = flight_doc
    if fault_plan is not None:
        out["fault_events"] = fault_plan.events()
    if sentinel is not None:
        out["sentinel"] = sentinel.report()
    return out
