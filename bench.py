"""Benchmark entry point — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): AlexNet ImageNet images/sec. Runs the real
SPMD training step (fwd/bwd/goo update, ZeRO-1 sharded state) on synthetic
ImageNet-shaped data on whatever devices are available (the driver runs this
on real TPU hardware).

``vs_baseline`` is reported as 1.0: the reference publishes no benchmark
numbers (``BASELINE.json "published": {}``; see BASELINE.md), so there is no
external denominator — the recorded value itself becomes the cross-round
baseline.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


def bench_alexnet(batch_per_device: int = 64, steps: int = 20, warmup: int = 3):
    import mpit_tpu
    from mpit_tpu import opt as gopt
    from mpit_tpu.data import shard_batch, synthetic_imagenet
    from mpit_tpu.models import AlexNet
    from mpit_tpu.train import make_train_step

    world = mpit_tpu.init()
    n = world.num_devices
    global_batch = batch_per_device * n

    model = AlexNet(num_classes=1000)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 224, 224, 3), jnp.float32)
    )["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["image"])
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(
            jnp.take_along_axis(logp, batch["label"][:, None], axis=1)
        )
        return loss, {}

    tx = gopt.goo(0.01, 0.9)
    init_fn, step_fn, _ = make_train_step(loss_fn, tx, world, zero1=True)
    state = init_fn(params)

    # Two pre-staged batches, alternated, so no step can be served from a
    # cached/identical-input artifact; successive steps still chain through
    # the state dependency, so the final block times the whole run.
    ds = synthetic_imagenet()
    stream = ds.batches(global_batch)
    batches = [shard_batch(world, next(stream)) for _ in range(2)]

    for i in range(warmup):
        state, metrics = step_fn(state, batches[i % 2])
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step_fn(state, batches[i % 2])
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    images_per_sec = global_batch * steps / dt
    return {
        "metric": "alexnet_imagenet_images_per_sec",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": 1.0,
        "detail": {
            "devices": n,
            "platform": jax.devices()[0].platform,
            "global_batch": global_batch,
            "steps": steps,
            "final_loss": round(float(metrics["loss"]), 4),
        },
    }


if __name__ == "__main__":
    print(json.dumps(bench_alexnet()))
