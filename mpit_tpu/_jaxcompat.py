"""Forward-compat gate: run the jax-0.9-targeted codebase on older jax.

This framework is written against jax 0.9's API surface (``jax.typeof``
with VMA-typed avals, ``lax.axis_size``, top-level ``jax.shard_map`` with
``check_vma``, ``jax.sharding.AxisType``, varying→invariant
``all_gather_invariant``). Some environments (this build container: jax
0.4.37) predate all of those. Per the repo rule "stub or gate missing
deps", this module installs *semantics-preserving* fallbacks onto the
``jax``/``lax`` namespaces at import time, so the hundreds of call sites
keep reading as the 0.9 code they are:

- ``lax.axis_size(name)`` → ``lax.psum(1, name)``, which constant-folds
  to a concrete int under tracing on every jax since 0.2.
- ``jax.typeof(x)`` → ``jax.core.get_aval(x)``. Call sites only ever do
  ``getattr(jax.typeof(x), "vma", ...)``; pre-VMA avals simply have no
  ``vma`` attribute and the fallback branch is taken — correct, because
  pre-0.9 shard_map has no varying/replicated type system to satisfy.
- ``jax.shard_map(..., check_vma=...)`` →
  ``jax.experimental.shard_map.shard_map(..., check_rep=False)``. The
  VMA checker does not exist pre-0.9; its closest ancestor
  (``check_rep``) enforces *different* (stricter, psum-inserting)
  replication rules that the VMA-era code deliberately opts out of via
  ``vary()`` — so the honest mapping is "off". Gradient semantics are
  unchanged: grads of replicated params stay device-local and the train
  step owns its one reduction, exactly what ``collectives.vary``
  arranges under 0.9 (see its docstring).
- ``vary()``'s ``pvary`` retype and the invariant all-gather degrade to
  identity / plain ``lax.all_gather`` — they are *type-system* markers;
  the runtime data movement is identical.

Nothing is patched when running under a jax that already provides the
real API (``hasattr`` gates everywhere), so on 0.9 this module is inert.
"""

from __future__ import annotations

import jax
from jax import lax

# True when this jax has the VMA (varying/replicated) type system — the
# 0.9-era API this codebase targets natively. Cross-tier gradient parity
# (the 3-D and EP tiers' single-device-exactness) depends on VMA AD
# semantics; tests for it skip on pre-VMA jax.
HAS_VMA = hasattr(jax, "typeof")

# True when pallas ships the TPU interpret mode (pltpu.InterpretParams) —
# the multi-"device" remote-DMA/semaphore simulator the ring-allreduce
# kernel's CPU tests require. The pre-0.9 generic pallas interpreter
# cannot simulate cross-device DMA, so those tests skip without this.
try:
    from jax.experimental.pallas import tpu as _pltpu_probe

    HAS_TPU_INTERPRET = hasattr(_pltpu_probe, "InterpretParams")
except ImportError:  # pallas TPU backend absent entirely
    HAS_TPU_INTERPRET = False


def _axis_size(name) -> int:
    # psum of a Python scalar constant-folds to the concrete axis size.
    return lax.psum(1, name)


def _typeof(x):
    return jax.core.get_aval(x)


def all_gather_invariant(x, axis_name, *, axis: int = 0, tiled: bool = False):
    """Pre-0.9 stand-in: plain all_gather (the result IS identical on
    every device; only the 0.9 VMA *typing* of that fact is missing)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def pvary(x, names):
    """Pre-0.9 stand-in for the replicated→varying retype: identity.
    Without a VMA checker there is nothing to retype for."""
    del names
    return x


def _shard_map(f=None, *, mesh=None, in_specs=None, out_specs=None,
               check_vma: bool = True):
    from jax.experimental.shard_map import shard_map as _sm

    del check_vma  # no VMA checker to configure pre-0.9 (docstring above)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def install() -> None:
    """Install the fallbacks onto jax/lax where the real API is absent."""
    if not hasattr(lax, "axis_size"):
        lax.axis_size = _axis_size
    if not hasattr(jax, "typeof"):
        jax.typeof = _typeof
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map

    import inspect

    try:
        params = inspect.signature(jax.ShapeDtypeStruct.__init__).parameters
        accepts_vma = "vma" in params
    except (TypeError, ValueError):  # C-implemented signature: assume new
        accepts_vma = True
    if not accepts_vma:
        _Real = jax.ShapeDtypeStruct

        class _VmaShapeDtypeStruct(_Real):
            """0.9's ``ShapeDtypeStruct(..., vma=...)`` on pre-VMA jax:
            the vma annotation (how a Pallas out_shape varies across
            mesh axes) has no pre-0.9 counterpart — drop it. Subclass,
            not factory, so ``isinstance(x, jax.ShapeDtypeStruct)``
            keeps working for instances made through the public name."""

            def __init__(self, shape, dtype, *, vma=None, **kw):
                del vma
                super().__init__(shape, dtype, **kw)

        jax.ShapeDtypeStruct = _VmaShapeDtypeStruct

    # Pallas-TPU interpret params: 0.9 spells interpret mode as
    # ``interpret=pltpu.InterpretParams(...)``; old pallas takes a bool.
    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "InterpretParams"):
            def _interpret_params(**kw):
                del kw
                return True

            pltpu.InterpretParams = _interpret_params
        if not hasattr(pltpu, "CompilerParams") and hasattr(
            pltpu, "TPUCompilerParams"
        ):
            def _compiler_params(**kw):
                allowed = set(
                    inspect.signature(pltpu.TPUCompilerParams).parameters
                )
                return pltpu.TPUCompilerParams(
                    **{k: v for k, v in kw.items() if k in allowed}
                )

            pltpu.CompilerParams = _compiler_params
    except ImportError:
        pass


def make_mesh(axis_sizes, axis_names):
    """``jax.make_mesh`` with AxisType.Auto where the type exists (0.9:
    the default of Explicit leaks sharding-in-types avals into host-level
    ops), and without the argument where it doesn't (pre-0.9 meshes have
    no axis types — every axis already behaves like Auto)."""
    if hasattr(jax.sharding, "AxisType"):
        axis_types = (jax.sharding.AxisType.Auto,) * len(axis_names)
        return jax.make_mesh(tuple(axis_sizes), tuple(axis_names), axis_types)
    return jax.make_mesh(tuple(axis_sizes), tuple(axis_names))


def mesh_from_devices(dev_array, axis_names):
    """``jax.sharding.Mesh`` from an explicit device array, axis-typed
    Auto on 0.9 (same rationale as :func:`make_mesh`)."""
    from jax.sharding import Mesh

    if hasattr(jax.sharding, "AxisType"):
        axis_types = (jax.sharding.AxisType.Auto,) * len(axis_names)
        return Mesh(dev_array, tuple(axis_names), axis_types=axis_types)
    return Mesh(dev_array, tuple(axis_names))


install()
