"""ctypes bindings for the native (C++) data-pipeline core.

The reference's native stratum marshals raw Torch tensor pointers across
the Lua/C/MPI boundary (SURVEY.md §2 L0, §3.1 C1); this module is the
framework's host-side counterpart: batch production runs in C++ worker
threads (``mpit_tpu/native/data_loader.cpp``) that overlap training
without the GIL, handing buffers across the boundary through a slot ring.
By default each batch is copied out of its slot at the boundary (one
memcpy — ``jax.device_put`` gives no host-buffer completion signal, so
recycling a slot under a pending transfer would corrupt batches; see
``_SlotIterator``); ``copy=False`` gives true zero-copy views for
consumers that fully read each batch before advancing.

Build: compiled on first use via the in-tree Makefile (``g++`` is part of
the environment; SURVEY.md §8.1). If the toolchain or build fails,
importers fall back to the pure-Python generators in
:mod:`mpit_tpu.data.synthetic` — same shapes, same learnable structure.
Set ``MPIT_NATIVE=0`` to force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Iterator

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libmpit_data.so"
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_BUILD_ERROR: str | None = None


def _load() -> ctypes.CDLL | None:
    """Build (once) and load the native library; None if unavailable."""
    global _LIB, _BUILD_ERROR
    if os.environ.get("MPIT_NATIVE", "1") == "0":
        return None
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _BUILD_ERROR is not None:
            return None
        # Always invoke make: it no-ops when the .so is fresh and rebuilds
        # when the C++ source is newer (a stale pre-upgrade .so would lack
        # newer symbols, e.g. mpit_cls_create_aug).
        try:
            subprocess.run(
                ["make", "-C", str(_NATIVE_DIR)],
                check=True,
                capture_output=True,
                text=True,
            )
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            if not _LIB_PATH.exists():
                _BUILD_ERROR = getattr(e, "stderr", str(e)) or str(e)
                return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
            _declare(lib)
        except (OSError, AttributeError) as e:
            # AttributeError: a stale pre-upgrade .so survived a failed
            # rebuild and lacks newer symbols — degrade to the Python
            # generators like any other unavailable-native case.
            _BUILD_ERROR = str(e)
            return None
        _LIB = lib
        return lib


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.mpit_cls_create.restype = c.c_void_p
    lib.mpit_cls_create.argtypes = [
        c.POINTER(c.c_float), c.c_int, c.c_int64, c.c_float, c.c_uint64,
        c.c_int, c.c_int, c.c_int,
    ]
    lib.mpit_cls_create_aug.restype = c.c_void_p
    lib.mpit_cls_create_aug.argtypes = [
        c.POINTER(c.c_float), c.c_int, c.c_int64, c.c_float, c.c_uint64,
        c.c_int, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        c.c_int,
    ]
    lib.mpit_cls_image_ptr.restype = c.POINTER(c.c_float)
    lib.mpit_cls_image_ptr.argtypes = [c.c_void_p, c.c_int]
    lib.mpit_cls_label_ptr.restype = c.POINTER(c.c_int32)
    lib.mpit_cls_label_ptr.argtypes = [c.c_void_p, c.c_int]
    lib.mpit_cls_next_slot.restype = c.c_int
    lib.mpit_cls_next_slot.argtypes = [c.c_void_p]
    lib.mpit_cls_release_slot.argtypes = [c.c_void_p, c.c_int]
    lib.mpit_cls_destroy.argtypes = [c.c_void_p]

    lib.mpit_lm_create.restype = c.c_void_p
    lib.mpit_lm_create.argtypes = [
        c.POINTER(c.c_int32), c.c_int, c.c_int, c.c_int, c.c_uint64,
        c.c_int, c.c_int, c.c_int,
    ]
    lib.mpit_lm_tokens_ptr.restype = c.POINTER(c.c_int32)
    lib.mpit_lm_tokens_ptr.argtypes = [c.c_void_p, c.c_int]
    lib.mpit_lm_next_slot.restype = c.c_int
    lib.mpit_lm_next_slot.argtypes = [c.c_void_p]
    lib.mpit_lm_release_slot.argtypes = [c.c_void_p, c.c_int]
    lib.mpit_lm_destroy.argtypes = [c.c_void_p]

    lib.mpit_rrc_batch.argtypes = [
        c.POINTER(c.c_float), c.POINTER(c.c_float),
        c.c_int, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        c.c_uint64, c.c_uint64,
        c.c_float, c.c_float, c.c_float, c.c_float, c.c_int,
    ]


def available() -> bool:
    """Whether the native core can be (or was) built and loaded."""
    return _load() is not None


def build_error() -> str | None:
    """The captured build/load failure, for diagnostics."""
    _load()
    return _BUILD_ERROR


class _SlotIterator:
    """Slot-ring consumption: blocking next, explicit lifecycle.

    ``copy=True`` (default) hands out an owned numpy copy of each slot and
    releases the slot immediately — safe for any consumer, including
    ``jax.device_put``, whose host-side read has NO completion signal
    (``block_until_ready`` can return before the transfer thread has read
    the buffer; observed as batch corruption on the CPU backend when a
    recycled slot was overwritten mid-transfer). The C++ win is the
    native-threaded *generation*; one memcpy per batch is noise next to it.

    ``copy=False`` yields zero-copy views valid only until the next
    ``__next__`` call — for consumers that fully read the batch (into
    their own memory) before advancing.
    """

    def __init__(self, lib, handle, next_fn, release_fn, destroy_fn, views, copy):
        self._lib = lib
        self._h = handle
        self._next = next_fn
        self._release = release_fn
        self._destroy = destroy_fn
        self._views = views  # slot -> batch dict of numpy views
        self._copy = copy
        self._held: int | None = None
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        if self._held is not None:
            self._release(self._h, self._held)
            self._held = None
        slot = self._next(self._h)
        if slot < 0:
            raise StopIteration
        if self._copy:
            batch = {k: np.array(v) for k, v in self._views[slot].items()}
            self._release(self._h, slot)
            return batch
        self._held = slot
        return self._views[slot]

    def close(self):
        if not self._closed:
            self._closed = True
            if self._held is not None:
                self._release(self._h, self._held)
                self._held = None
            self._destroy(self._h)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # best-effort; explicit close preferred
        try:
            self.close()
        except Exception:
            pass


def _check_ring(depth: int, threads: int) -> None:
    """Reject ring configs that would hang rather than fail: threads=0
    builds a loader with no producers (the first ``__next__`` blocks
    forever in C++ ``pop_ready``); depth=0 deadlocks ``claim_free``."""
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    if threads < 1:
        raise ValueError(f"threads must be >= 1, got {threads}")


def classification_stream(
    prototypes: np.ndarray,
    *,
    noise: float,
    batch_size: int,
    seed: int = 0,
    depth: int = 4,
    threads: int = 2,
    copy: bool = True,
    augment: bool = False,
    crop_pad: int = 4,
    hflip: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Native prototype+noise stream: ``{"image", "label"}`` batches.

    ``prototypes``: float32 ``[num_classes, *sample_shape]``. Raises
    ``RuntimeError`` if the native core is unavailable (callers that want
    graceful degradation check :func:`available` first). ``augment``
    applies the in-worker shift-crop + hflip (requires ``[H, W, C]``
    samples; same transforms as ``data/augment.py``).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native data core unavailable: {_BUILD_ERROR}")
    _check_ring(depth, threads)
    protos = np.ascontiguousarray(prototypes, np.float32)
    num_classes = protos.shape[0]
    sample_shape = protos.shape[1:]
    elems = int(np.prod(sample_shape))
    if augment:
        if len(sample_shape) != 3:
            raise ValueError(
                f"augment requires [H, W, C] samples, got {sample_shape}"
            )
        h, w, ch = (int(d) for d in sample_shape)
        handle = lib.mpit_cls_create_aug(
            protos.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            num_classes, elems, float(noise), seed, batch_size, depth,
            threads, h, w, ch, int(crop_pad), int(bool(hflip)),
        )
    else:
        handle = lib.mpit_cls_create(
            protos.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            num_classes, elems, float(noise), seed, batch_size, depth, threads,
        )
    views = {}
    for s in range(depth):
        img = np.ctypeslib.as_array(
            lib.mpit_cls_image_ptr(handle, s), shape=(batch_size, *sample_shape)
        )
        lab = np.ctypeslib.as_array(
            lib.mpit_cls_label_ptr(handle, s), shape=(batch_size,)
        )
        views[s] = {"image": img, "label": lab}
    return _SlotIterator(
        lib, handle, lib.mpit_cls_next_slot, lib.mpit_cls_release_slot,
        lib.mpit_cls_destroy, views, copy,
    )


def lm_stream(
    successors: np.ndarray,
    *,
    seq_len: int,
    batch_size: int,
    seed: int = 0,
    depth: int = 4,
    threads: int = 2,
    copy: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Native bigram-walk token stream: ``{"tokens": [B, L+1]}`` batches."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native data core unavailable: {_BUILD_ERROR}")
    _check_ring(depth, threads)
    table = np.ascontiguousarray(successors, np.int32)
    vocab, branching = table.shape
    handle = lib.mpit_lm_create(
        table.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vocab, branching, seq_len, seed, batch_size, depth, threads,
    )
    views = {
        s: {
            "tokens": np.ctypeslib.as_array(
                lib.mpit_lm_tokens_ptr(handle, s),
                shape=(batch_size, seq_len + 1),
            )
        }
        for s in range(depth)
    }
    return _SlotIterator(
        lib, handle, lib.mpit_lm_next_slot, lib.mpit_lm_release_slot,
        lib.mpit_lm_destroy, views, copy,
    )


def rrc_batch(
    images: np.ndarray,
    *,
    seed: int,
    ticket: int,
    out_hw: tuple[int, int] | None = None,
    scale: tuple[float, float] = (0.08, 1.0),
    ratio: tuple[float, float] = (3 / 4, 4 / 3),
    hflip: bool = True,
) -> np.ndarray | None:
    """Native random-resized-crop of one ``[B, H, W, C]`` float32 batch.

    The C++ counterpart of ``data/augment.py::random_resized_crop`` for
    the file-backed (real-image) pipeline: same sampling scheme and
    counter-seeding shape (one ``(seed, ticket)`` stream per batch), the
    established bit-different / distribution-identical native contract,
    and the per-pixel bilinear loop runs off the GIL. Returns None when
    the native build is unavailable (caller falls back to numpy).
    """
    lib = _load()
    if lib is None:
        return None
    images = np.ascontiguousarray(images, np.float32)
    if images.ndim != 4:
        raise ValueError(f"expected [B,H,W,C] images, got {images.shape}")
    b, h, w, c = images.shape
    oh, ow = out_hw if out_hw is not None else (h, w)
    out = np.empty((b, oh, ow, c), np.float32)
    lib.mpit_rrc_batch(
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        b, h, w, c, oh, ow,
        ctypes.c_uint64(seed & (2**64 - 1)),
        ctypes.c_uint64(ticket & (2**64 - 1)),
        float(scale[0]), float(scale[1]),
        float(ratio[0]), float(ratio[1]),
        1 if hflip else 0,
    )
    return out
