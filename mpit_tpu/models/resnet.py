"""ResNet-50 — the sync-allreduce + sharded-goo workload (config #4).

Not in the reference (which stops at AlexNet); enters via the acceptance
ladder ("ImageNet ResNet-50 (sync allreduce path, sharded goo optimizer)",
BASELINE.json). Standard bottleneck-v1.5 architecture (stride on the 3×3).

TPU notes: NHWC layout; BatchNorm statistics are per-device by default —
the train step syncs them with a ``pmean`` when cross-replica BN is enabled
(the sync-DP semantics of config #4 concern gradients; BN sync is optional
as in most data-parallel trainers). bfloat16 compute, float32 params and
BN stats.

Round-4 perf levers (the standard TPU ResNet recipe; measured in
BENCHMARKS.md):

- ``norm_dtype=bfloat16`` (default): BN *statistics* still accumulate in
  float32 (flax upcasts internally) but the normalized activations stay
  bf16 — without this every BN+relu bounces activations through f32,
  doubling the HBM traffic of every block's elementwise tail.
- ``stem="s2d"`` (default): 2×2 space-to-depth on the input
  ([B,224,224,3] → [B,112,112,12]) + a 4×4/stride-1 conv replacing the
  7×7/stride-2 stem. Same receptive field (7 taps at stride 2 span 8
  pixels = 4 s2d cells), 12 input channels instead of 3 (less MXU
  contraction-dim padding), and a stride-1 conv the TPU convolution
  tiling prefers. Trained from scratch this is a reparameterization,
  not a pretrained-weight transform.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from mpit_tpu.models.norm import ScaleShiftBatchNorm


class Bottleneck(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16
    norm: Any = ScaleShiftBatchNorm
    norm_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        norm = partial(
            self.norm, use_running_average=not train, dtype=self.norm_dtype
        )
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = nn.relu(norm()(y))
        y = nn.Conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            padding=[(1, 1), (1, 1)], use_bias=False, dtype=self.dtype,
        )(y)
        y = nn.relu(norm()(y))
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN scale
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                use_bias=False, dtype=self.dtype,
            )(residual)
            residual = norm()(residual)
        return nn.relu(residual + y)


class ResNet50(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    norm_dtype: Any = jnp.bfloat16
    stem: str = "s2d"  # "s2d" (TPU recipe) | "conv7" (classic)
    # BN implementation: ScaleShiftBatchNorm (models/norm.py — the
    # round-5 BN-train lever, measured in BENCHMARKS.md) or
    # nn.BatchNorm (the flax oracle; identical math, parity-tested).
    norm: Any = ScaleShiftBatchNorm

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        """``train=True``: BN uses batch statistics and updates the
        ``batch_stats`` collection (apply with ``mutable=['batch_stats']``).
        ``train=False``: BN normalizes with the running averages — the
        inference-mode path eval metrics must use (round-1 advisor)."""
        x = x.astype(self.dtype)
        if self.stem == "s2d":
            b, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError(
                    f"stem='s2d' needs even input H/W (got {h}x{w}); use "
                    "an even --train-size or stem='conv7'"
                )
            x = x.reshape(b, h // 2, 2, w // 2, 2, c).transpose(
                0, 1, 3, 2, 4, 5
            ).reshape(b, h // 2, w // 2, 4 * c)
            x = nn.Conv(
                64, (4, 4), strides=(1, 1), padding=[(1, 2), (1, 2)],
                use_bias=False, dtype=self.dtype,
            )(x)
        else:
            x = nn.Conv(
                64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                use_bias=False, dtype=self.dtype,
            )(x)
        x = nn.relu(
            self.norm(
                use_running_average=not train, dtype=self.norm_dtype
            )(x)
        )
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = Bottleneck(
                    64 * 2**stage, strides=strides, dtype=self.dtype,
                    norm=self.norm, norm_dtype=self.norm_dtype,
                )(x, train=train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
