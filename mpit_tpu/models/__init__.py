"""mpit_tpu.models — the workload model zoo.

The reference defines its models inline in the Torch7 training scripts
(LeNet-style convnet for MNIST, AlexNet for ImageNet; SURVEY.md §3.2
A4/A5). Here they are first-class flax modules, plus the models the
acceptance ladder adds beyond the reference (BASELINE.json configs #4/#5):

- :class:`LeNet`     — MNIST convnet (config #1/#2).
- :class:`AlexNet`   — ImageNet workhorse (config #3; north-star ≥58% top-1).
- :class:`ResNet50`  — sync-DP + sharded-goo config (#4).
- :class:`GPT2`      — transformer stretch config (#5), built on
  :mod:`mpit_tpu.parallel` layers so TP/SP/CP shardings apply.

All image models take NHWC float32/bfloat16 inputs; compute-heavy matmuls
run in bfloat16 (MXU-native) with float32 params unless configured
otherwise.
"""

from mpit_tpu.models.lenet import LeNet
from mpit_tpu.models.alexnet import AlexNet
from mpit_tpu.models.norm import ScaleShiftBatchNorm
from mpit_tpu.models.resnet import ResNet50
from mpit_tpu.models.gpt2 import GPT2, GPT2Config

__all__ = [
    "LeNet",
    "AlexNet",
    "ResNet50",
    "GPT2",
    "GPT2Config",
    "ScaleShiftBatchNorm",
]
