"""Benchmark entry point — prints ONE compact JSON line for the driver.

Headline metric (BASELINE.json): AlexNet ImageNet images/sec, measured on
the real SPMD training step (fwd/bwd/goo update, ZeRO-1 sharded state) on
whatever devices are available. Secondary metrics ride in ``detail``:
GPT-2 tokens/sec (the stretch config), ResNet-50 images/sec, the EP-tier
MoE tokens/sec, GPT-2 serving decode tokens/sec + request-latency
p50/p95 on the continuous-batching engine (``mpit_tpu.serve``, ISSUE 4),
and — when >1 device is present — measured allreduce GB/s (modeled
otherwise, labeled as such; SURVEY.md §8.4.5).

Driver contract (round-5 hardening — the round-3 record outgrew the
driver's 2,000-char tail buffer and the round-4 run outgrew its time
budget, so BOTH contract dimensions are now budgeted explicitly):

* **Line budget.** The printed line carries headline value + per-workload
  essentials only and is pinned < 1,500 chars by a unit test
  (``tests/test_bench_contract.py``; target ≤ 1,200). Everything bulky —
  scaling projections, comm-model assumptions, drop-rate lists — goes to
  ``BENCH_DETAIL.json`` next to this file, which the line references.
* **Time budget.** (a) The persistent XLA compilation cache is enabled
  (``.jax_cache/``, verified working against this environment's axon PJRT
  backend: a 2.3 s compile replays in 0.04 s), so driver reruns skip the
  multi-minute compiles the build session already paid for. (b) Workloads
  run headline-first. (c) An elapsed-time budget (``MPIT_BENCH_BUDGET_S``,
  default 420 s) is checked before each workload; once exceeded, the rest
  are skipped and recorded under ``"truncated"``. (d) A daemon-thread
  watchdog 20% past the soft budget force-prints the record-so-far and
  exits 0 (a thread, not SIGALRM: it fires even while the main thread
  is blocked in a GIL-releasing native call — compile or device fetch).
* **Progressive emission.** The record line is (re)printed after EVERY
  completed workload — each print is a complete, parseable, compact
  record of everything measured so far (later workloads listed in
  ``"pending"``). If the driver kills the process anyway, the last
  complete line is still inside its tail window. Only the final line
  lacks a ``"pending"`` key.

Timing methodology: each timed window ends by fetching a *host value*
derived from the final step (``float(loss)``), not ``block_until_ready``
— on this environment's remote-attached TPU, block_until_ready can
return before execution completes, inflating throughput by orders of
magnitude (observed 258k img/s vs a real ~20k).

Dispatch amortization: the tunneled chip costs ~10–15 ms per host→device
dispatch (measured round 2 — comparable to an entire step, and it was
the round-1 ceiling). Steps therefore run in scanned chunks of K inside
one compiled call (``make_train_step(scan_steps=K)``): every step still
executes fully on device over distinct pre-staged batches; the wall
clock is real; only the host round-trips between steps — pure tunnel
artifact — are gone. The app-path (one dispatch per step) cross-check is
reported alongside and is the headline (round-3 verdict item 10).

App-path gap (ISSUE 2): for the workloads with an app-path cross-check
(AlexNet, GPT-2) the same single-dispatch step also runs under the
production ``hardened_loop`` over the same pre-staged batches;
``app_path_overhead_pct`` = 1 − hardened/raw rides the record line, and
the obs span attribution for exactly that window
(``gap_attribution``) goes to BENCH_DETAIL.json — so the loop's host-
path tax is a first-class, regression-pinned metric rather than an
anecdote.

``vs_baseline``: the reference publishes no benchmark numbers
(BASELINE.json ``"published": {}``; see BASELINE.md), so per the round-1
verdict the *round-1 recorded values* are the cross-round baseline —
``vs_baseline`` is the ratio to ``BENCH_r01.json`` (read at runtime;
falls back to the recorded constants if the file is gone).
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time

_REPO = os.path.dirname(os.path.abspath(__file__))


def _enable_compile_cache() -> None:
    """Persistent XLA compile cache — MUST run before the first trace.

    Called from :func:`main`, NOT at import: tests import this module
    for the record builder, and enabling a process-global cache as an
    import side effect poisoned the whole test process (cache entries
    written by a different jaxlib/backend deserialize into executables
    the host backend crashes on — observed as a segfault in the first
    jitted train step of any test that ran after an `import bench`).
    """
    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(_REPO, ".jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _timed_steps(step_fn, state, batches, n):
    """Run n chunk-calls alternating pre-staged (stacked) batches; returns
    (dt, loss, state). The window closes on a host-value fetch (see module
    docstring)."""
    from mpit_tpu import obs

    with obs.span("timed_window", calls=n):
        t0 = time.perf_counter()
        metrics = {}
        for i in range(n):
            state, metrics = step_fn(state, batches[i % 2])
        loss = float(metrics["loss"])  # forces completion of the whole chain
        return time.perf_counter() - t0, loss, state


def _best_window(step_fn, state, batches, steps, repeats=3):
    """Best-of-N timed windows: the tunneled chip in this environment
    shows transient multi-x slowdowns (relay contention), so a single
    window can under-report by an order of magnitude; the fastest window
    approximates uncontended hardware."""
    best_dt, loss = float("inf"), float("nan")
    for _ in range(repeats):
        dt, loss, state = _timed_steps(step_fn, state, batches, steps)
        best_dt = min(best_dt, dt)
    return best_dt, loss, state


def _measure(step_fn, state, batches, *, calls, scan_steps, warmup):
    """The shared timed-run scaffold (warmup, then best-of-N windows):
    every bench measures through this one path so the methodology cannot
    drift between workloads. Returns ``(dt, steps, final_loss, state)``."""
    from mpit_tpu import obs

    with obs.span("warmup", calls=warmup):
        _, _, state = _timed_steps(step_fn, state, batches, warmup)
    dt, final_loss, state = _best_window(step_fn, state, batches, calls)
    return dt, calls * scan_steps, final_loss, state


def _hardened_gap(
    world, app_step_fn, state, device_batches, *, items, raw_rate,
    steps=24, log_every=4,
):
    """The app-path gap, measured (ISSUE 2 tentpole): run the SAME
    single-dispatch step under the production ``hardened_loop`` over the
    same pre-staged device batches (``transform`` = identity, so no host
    input work rides along) and compare its steady-state items/sec with
    the raw best-window rate. ``app_path_overhead_pct`` is the loop's
    own host-path tax — fences, guard, logging, prefetch plumbing — the
    async metric pipeline (train/loop.py ``fetch_lag``) exists to close.
    The obs span attribution for exactly this window rides along
    (``gap_attribution``), so BENCH_DETAIL.json shows WHERE the
    remaining overhead sits, not just how big it is."""
    from mpit_tpu import obs
    from mpit_tpu.train.loop import hardened_loop
    from mpit_tpu.train.metrics import MetricLogger

    def cycle():
        i = 0
        while True:
            yield device_batches[i % 2]
            i += 1

    rec = obs.get_recorder()
    n0 = rec.event_count() if rec else 0
    with obs.span("hardened_loop", steps=steps):
        out = hardened_loop(
            world,
            state,
            app_step_fn,
            cycle(),
            steps=int(state.step) + steps,
            items_per_batch=items,
            log_every=log_every,
            logger=MetricLogger(stdout=False),
            transform=lambda b: b,  # batches are already placed
        )
    res = {"hardened_items_per_sec": out.get("items_per_sec")}
    if res["hardened_items_per_sec"] and raw_rate:
        res["app_path_overhead_pct"] = round(
            100.0 * (1.0 - res["hardened_items_per_sec"] / raw_rate), 2
        )
    if rec is not None:
        res["gap_attribution"] = obs.gap_attribution(rec.summary(since=n0))
    return res, out["state"]


def _roofline_block(step_fn, args, step_seconds, *, steps_per_call=1,
                    ici_bytes=0.0, phase="step"):
    """Measured-vs-modeled utilization for one training step (ISSUE 8):
    the step's ``cost_analysis()`` FLOPs/bytes (one extra AOT compile —
    a persistent-cache replay of HLO the run already compiled), divided
    down to per-step, registered with the workload's recorder under
    ``phase`` (so BENCH_DETAIL's obs_baseline carries the per-phase
    roofline table), and reconciled against the MEASURED step time.
    Returns ``(block, mfu_pct)`` — percentages only on TPU; off-chip
    the block records modeled cost + platform, never a fabricated MFU.
    ``ici_bytes``: modeled per-step gradient-sync wire bytes at the
    REAL device count (0 on one chip — never a hypothetical pod's)."""
    from mpit_tpu import obs
    from mpit_tpu.obs import roofline as R
    from mpit_tpu.utils import TPU_V5E, roofline as roofline_model

    platform = jax.devices()[0].platform
    try:
        with obs.span("roofline_cost"):
            cost = R.cost_from_fn(step_fn, *args)
    except Exception as e:
        return (
            {"error": f"{type(e).__name__}: {e}"[:160],
             "platform": platform},
            None,
        )
    flops = cost["flops"] / steps_per_call
    hbm = cost["hbm_bytes"] / steps_per_call
    R.register_cost(
        phase, flops=flops, hbm_bytes=hbm, ici_bytes=ici_bytes,
        platform=platform,
    )
    block = {
        "flops_per_step": flops,
        "hbm_bytes_per_step": hbm,
        "ici_bytes_per_step_modeled": ici_bytes,
        "arithmetic_intensity": round(flops / hbm, 2) if hbm else None,
        "measured_step_seconds": round(step_seconds, 6),
        "platform": platform,
        "chip": TPU_V5E.name,
    }
    mfu = None
    if flops or hbm:
        model = roofline_model(flops, hbm, ici_bytes=ici_bytes)
        block["roofline_step_seconds_lower_bound"] = round(
            model["seconds_lower_bound"], 6
        )
        block["bound_modeled"] = model["bound"]
        if platform == "tpu" and step_seconds > 0:
            util = R.utilization(
                {"flops": flops, "hbm_bytes": hbm, "ici_bytes": ici_bytes},
                step_seconds, platform=platform, peaks=R.chip_peaks(),
            )
            block.update({
                k: util[k]
                for k in ("mfu_pct", "hbm_util_pct", "ici_util_pct")
                if k in util
            })
            block["fraction_of_roofline"] = round(
                block["roofline_step_seconds_lower_bound"] / step_seconds, 4
            )
            mfu = block.get("mfu_pct")
    return block, mfu


def _stack_batches(world, stream, k: int, spec=None):
    """Stage k distinct batches on device as one [k, ...]-stacked chunk."""
    import numpy as np

    from mpit_tpu import obs
    from mpit_tpu.data import shard_batch

    with obs.span("staging", batches=k):
        host = [next(stream) for _ in range(k)]
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *host)
        return shard_batch(world, stacked, spec=spec)


def _device_image_batches(
    world, *, global_batch, hw, classes, spec, k=None, seed=0
):
    """Synthetic image batches generated ON DEVICE (jitted jax.random with
    explicit output shardings).

    Round-5 time-budget fix: host-generating AlexNet-sized batches and
    pushing them through this environment's tunneled device link staged
    ~7 GB per bench run — 2/3 of the cold run's 20-minute AlexNet phase
    was data transfer, which no compile cache helps. The timed window is
    input-INDEPENDENT dense compute (it starts after staging), so the
    pixels' provenance doesn't touch the measurement; uniform pixels +
    random labels on device replace the host stream. ``k``: stack depth
    for the scanned path (None = single unstacked batch).
    """
    from jax.sharding import NamedSharding

    from mpit_tpu import obs

    lead = () if k is None else (k,)
    out_shardings = {
        "image": NamedSharding(world.mesh, spec),
        "label": NamedSharding(world.mesh, spec),
    }

    @functools.partial(jax.jit, out_shardings=out_shardings)
    def gen(key):
        ki, kl = jax.random.split(key)
        return {
            "image": jax.random.uniform(
                ki, (*lead, global_batch, hw, hw, 3), jnp.float32
            ),
            "label": jax.random.randint(
                kl, (*lead, global_batch), 0, classes, jnp.int32
            ),
        }

    with obs.span("staging", on_device=True):
        return gen(jax.random.key(seed))


def bench_alexnet(
    batch_per_device: int = 2048,
    calls: int = 4,
    scan_steps: int = 2,
    warmup: int = 1,
):
    """AlexNet headline metric. Round-2 tuning: batch 2048 (512→2048
    measured 18.0k→22.2k img/s, ~52% MFU by the BENCHMARKS.md accounting;
    4096 exceeds what the chip's HBM can stage double-buffered)."""
    import mpit_tpu
    from jax.sharding import PartitionSpec as P
    from mpit_tpu import opt as gopt
    from mpit_tpu.models import AlexNet
    from mpit_tpu.train import make_train_step
    from mpit_tpu.utils import CommModel

    world = mpit_tpu.init()
    n = world.num_devices
    global_batch = batch_per_device * n

    model = AlexNet(num_classes=1000)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 224, 224, 3), jnp.float32)
    )["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["image"])
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(
            jnp.take_along_axis(logp, batch["label"][:, None], axis=1)
        )
        return loss, {}

    init_fn, step_fn, _ = make_train_step(
        loss_fn, gopt.goo(0.01, 0.9), world, zero1=True, scan_steps=scan_steps
    )
    state = init_fn(params)

    # Two pre-staged stacked chunks (scan_steps distinct batches each),
    # alternated, so no step can be served from a cached/identical-input
    # artifact; successive steps still chain through the state dependency.
    # Batches are generated ON DEVICE (_device_image_batches) — round 5
    # removed the multi-GB host→device staging that dominated the bench's
    # wall clock on the tunneled chip.
    batches = [
        _device_image_batches(
            world, global_batch=global_batch, hw=224, classes=1000,
            spec=P(None, "data"), k=scan_steps, seed=i,
        )
        for i in range(2)
    ]

    dt, steps, final_loss, state = _measure(
        step_fn, state, batches, calls=calls, scan_steps=scan_steps,
        warmup=warmup,
    )

    # App-path cross-check (round-2 verdict "what's weak" #6): the same
    # step WITHOUT scan-chunking — one host dispatch per step, the shape
    # the application loop actually runs. The gap vs the scanned number
    # is the tunnel's per-dispatch cost, not device time; reported so the
    # headline can't silently hide an app-path regression.
    _, app_step_fn, _ = make_train_step(
        loss_fn, gopt.goo(0.01, 0.9), world, zero1=True
    )
    single = [
        _device_image_batches(
            world, global_batch=global_batch, hw=224, classes=1000,
            spec=P("data"), seed=10 + i,
        )
        for i in range(2)
    ]
    _, _, state = _timed_steps(app_step_fn, state, single, 1)  # compile
    app_dt, _, state = _best_window(app_step_fn, state, single, 4)
    app_rate = round(global_batch * 4 / app_dt, 2)

    # The production-loop cross-check (ISSUE 2): same app-path step,
    # driven by hardened_loop — the overhead between the two is the
    # loop's own host path, now pipelined (train/loop.py fetch_lag).
    gap, state = _hardened_gap(
        world, app_step_fn, state, single,
        items=global_batch, raw_rate=app_rate,
    )

    comm = CommModel(params, n, zero1=True)
    # Utilization flight data (ISSUE 8): cost_analysis of the SAME
    # app-path step the headline measures, reconciled against its
    # measured per-step wall. mfu_pct rides the record line (None
    # off-TPU — platform-labeled, never fabricated).
    rb, mfu = _roofline_block(
        app_step_fn, (state, single[0]), app_dt / 4,
        ici_bytes=comm.grad_sync_bytes(),
    )
    return {
        "images_per_sec": round(global_batch * steps / dt, 2),
        "ms_per_step": round(dt / steps * 1e3, 2),
        "app_path_images_per_sec": app_rate,
        "mfu_pct": mfu,
        "global_batch": global_batch,
        "batch_per_device": batch_per_device,
        "steps": steps,
        "scan_steps": scan_steps,
        "final_loss": round(final_loss, 4),
        "grad_sync_bytes_per_step_modeled": comm.grad_sync_bytes(),
        "scaling": _scaling(dt / steps, batch_per_device, params),
        "roofline": rb,
        **gap,
    }


def _scaling(step_seconds, items_per_chip, params, **kw):
    """The BASELINE 8→256 scaling-efficiency artifact (analytic, labeled
    ``modeled``; utils/profiling.scaling_projection). Two topologies:
    ``single_slice`` (up to 256 chips of ICI — one v5e pod) and
    ``slice64`` (64-chip slices joined by DCN — the cross-slice cliff).
    Detail-file-only: these blobs are what overflowed the driver's tail
    buffer in round 3. Extra kwargs (the MoE alltoall terms) pass
    through to scaling_projection."""
    from mpit_tpu.utils import scaling_projection

    return {
        "single_slice": scaling_projection(
            step_seconds, items_per_chip, params, slice_size=256, **kw
        ),
        "slice64": scaling_projection(
            step_seconds, items_per_chip, params, slice_size=64, **kw
        ),
    }


def moe_alltoall_payload(cfg, moe, batch_per_device: int, seq: int) -> float:
    """Per-chip routed-token bytes crossing the expert all-to-all per
    STEP (modeled; the scaling projection's ISSUE 3 satellite input):
    each MoE layer shuffles ~k slots per local token, d_model bf16 each,
    over ``moe_alltoall_passes`` distinct all-to-alls."""
    local_tokens = batch_per_device * seq
    return moe_alltoall_passes(cfg, moe) * moe.k * local_tokens \
        * cfg.d_model * 2.0


def moe_alltoall_passes(cfg, moe) -> int:
    """Distinct all-to-alls per step: dispatch + return, forward +
    backward (4), per MoE layer — each pays ring-hop latency separately
    in the scaling model."""
    return 4 * (cfg.num_layers // moe.every)


def bench_resnet(
    batch_per_device: int = 256,
    calls: int = 3,
    scan_steps: int = 2,
    warmup: int = 1,
):
    """ResNet-50 — baseline config #4 (sync allreduce + ZeRO-1 sharded
    goo, BatchNorm riding the stateful step; bf16 conv path). Batch
    sweep on the real chip (round 3): 64→1220, 128→1401, 256→1718,
    512→1753 img/s — 256 is the knee; 512 doubles activation memory
    for +2%. Round 4 (models/resnet.py levers, measured): bf16 BN
    output 1778→2279 img/s (+28% — the f32 normalized activations were
    doubling every block's elementwise HBM traffic), space-to-depth stem
    →2299; batch 512 re-swept, still flat. Remaining gap attributed by
    trace in BENCHMARKS.md."""
    import mpit_tpu
    from jax.sharding import PartitionSpec as P
    from mpit_tpu import opt as gopt
    from mpit_tpu.models import ResNet50
    from mpit_tpu.train import make_train_step

    world = mpit_tpu.init()
    n = world.num_devices
    global_batch = batch_per_device * n

    model = ResNet50(num_classes=1000)
    variables = jax.jit(model.init)(
        jax.random.key(0), jnp.zeros((2, 224, 224, 3), jnp.float32)
    )
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(p, stats, batch):
        logits, mutated = model.apply(
            {"params": p, "batch_stats": stats},
            batch["image"],
            mutable=["batch_stats"],
        )
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(
            jnp.take_along_axis(logp, batch["label"][:, None], axis=1)
        )
        return loss, {}, mutated["batch_stats"]

    init_fn, step_fn, _ = make_train_step(
        loss_fn,
        gopt.goo(0.1, 0.9, weight_decay=1e-4),
        world,
        zero1=True,
        stateful=True,
        scan_steps=scan_steps,
    )
    state = init_fn(params, batch_stats)
    batches = [
        _device_image_batches(
            world, global_batch=global_batch, hw=224, classes=1000,
            spec=P(None, "data"), k=scan_steps, seed=i,
        )
        for i in range(2)
    ]

    dt, steps, final_loss, state = _measure(
        step_fn, state, batches, calls=calls, scan_steps=scan_steps,
        warmup=warmup,
    )
    from mpit_tpu.utils import CommModel

    # No app-path variant here: the scanned chunk's cost divides down
    # to per-step (every step inside the scan executes fully).
    rb, mfu = _roofline_block(
        step_fn, (state, batches[0]), dt / steps,
        steps_per_call=scan_steps,
        ici_bytes=CommModel(params, n, zero1=True).grad_sync_bytes(),
    )
    return {
        "images_per_sec": round(global_batch * steps / dt, 2),
        "ms_per_step": round(dt / steps * 1e3, 2),
        "mfu_pct": mfu,
        "global_batch": global_batch,
        "batch_per_device": batch_per_device,
        "steps": steps,
        "scan_steps": scan_steps,
        "final_loss": round(final_loss, 4),
        "scaling": _scaling(dt / steps, batch_per_device, params),
        "roofline": rb,
    }


def bench_gpt2(calls: int = 3, scan_steps: int = 8, warmup: int = 1, seq: int = 512):
    """GPT-2 stretch config: tokens/sec on the shard_map+ZeRO-1 tier.

    Round-2 tuning (all measured on the real chip, see BENCHMARKS.md):
    batch per device 32→48, bf16 head operands with the fused streaming
    LM-head loss (the [B,T,50257] f32 logits array is never
    materialized, ``ops/lm_head.py``). Round 3: the Pallas flash kernel
    now WINS at T=512 (94.4→60 GB/step HBM traffic; the round-2 loss was
    128-block tiles + f32 matmul operands — retuned to 512-blocks with
    bf16 operands/f32 accumulation it measures 110.5k vs XLA's 99.1k
    tok/s), so it is the default on TPU from T=512 up. Round 4
    (trace-driven, BENCHMARKS.md): head-packed flash layout (no q/k/v
    transposes) + unrolled LM-head vocab loops → 127.0–130.3k tok/s.
    Round 5: batch re-sweep — 48→132.5k @56 / 132.2k @64 (plateau),
    119.4k @80 (HBM pressure), compile-OOM @96; 56 is the new default
    (50.0% MFU; the remaining gap is the documented D=64/LM-head bound,
    BENCHMARKS.md §GPT-2 ceiling).
    """
    import mpit_tpu
    from jax.sharding import PartitionSpec as P
    from mpit_tpu.data import SyntheticLM
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.train import make_train_step

    world = mpit_tpu.init()
    n = world.num_devices
    batch = 56 * n
    on_tpu = jax.devices()[0].platform == "tpu"

    kw = dict(max_seq_len=seq, head_dtype=jnp.bfloat16)
    attention = "xla"
    if on_tpu and seq >= 512:
        from mpit_tpu.ops import flash_attention

        kw["attention_fn"] = flash_attention
        attention = "pallas-flash"
    cfg = GPT2Config.small(**kw)
    model = GPT2(cfg)
    params = jax.jit(model.init)(
        jax.random.key(0), jnp.zeros((1, seq), jnp.int32)
    )["params"]

    def loss_fn(p, b):
        return GPT2.fused_loss_fn(model, p, b["tokens"]), {}

    init_fn, step_fn, _ = make_train_step(
        loss_fn, goo_adam(3e-4), world, zero1=True, scan_steps=scan_steps
    )
    state = init_fn(params)
    stream = SyntheticLM(vocab_size=cfg.vocab_size).batches(batch, seq)
    batches = [
        _stack_batches(world, stream, scan_steps, spec=P(None, "data"))
        for _ in range(2)
    ]

    dt, steps, final_loss, state = _measure(
        step_fn, state, batches, calls=calls, scan_steps=scan_steps,
        warmup=warmup,
    )

    # App-path cross-check (round-3 verdict item 10): the same step with
    # one host dispatch per step — what the application loop delivers.
    from mpit_tpu.data import shard_batch

    _, app_step_fn, _ = make_train_step(
        loss_fn, goo_adam(3e-4), world, zero1=True
    )
    single = [
        shard_batch(world, next(stream)),
        shard_batch(world, next(stream)),
    ]
    _, _, state = _timed_steps(app_step_fn, state, single, 1)  # compile
    app_dt, _, state = _best_window(app_step_fn, state, single, 4)
    app_rate = round(batch * seq * 4 / app_dt, 1)

    gap, state = _hardened_gap(
        world, app_step_fn, state, single,
        items=batch * seq, raw_rate=app_rate,
    )

    from mpit_tpu.utils import CommModel

    rb, mfu = _roofline_block(
        app_step_fn, (state, single[0]), app_dt / 4,
        ici_bytes=CommModel(params, n, zero1=True).grad_sync_bytes(),
    )
    return {
        "tokens_per_sec": round(batch * seq * steps / dt, 1),
        "app_path_tokens_per_sec": app_rate,
        "mfu_pct": mfu,
        "ms_per_step": round(dt / steps * 1e3, 2),
        "batch": batch,
        "seq_len": seq,
        "scan_steps": scan_steps,
        "attention": attention,
        "final_loss": round(final_loss, 4),
        "scaling": _scaling(dt / steps, (batch // n) * seq, params),
        "roofline": rb,
        **gap,
    }


def bench_moe(calls: int = 4, warmup: int = 1, seq: int = 512, batch_per_device: int = 32):
    """GPT-2-MoE throughput on the EP TIER ITSELF (round-3 verdict item
    4): ``parallel/ep.py``'s train step — routed dispatch, capacity
    drops, per-placement-group flat ravel, and ZeRO-1 ON (the round-3
    tile-pad compile-OOM is fixed by opt/sharded.py's barrier-fenced
    lane-aligned layout, verified at this exact 322M shape by
    ``compile_multichip.py``). One chip = ``data=1, expert=1`` mesh; the
    all-to-all is a local no-op, everything else is the pod code path.
    8 experts, top-2, cf=1.25, MoE every 2nd block. Dispatch/drop stats
    come from the model's sown ``dispatch_stats`` on a probe forward
    (high drop rates are expected here: the router is at random init).

    Round 5: the sort (ragged scatter/gather) dispatch replaced the
    one-hot einsum as the default — the [S, E, C] tensors that OOMed
    B=32/T=512 on the 16 GB chip (round-4 cap at B=16) no longer exist,
    so the tier now measures at B=32 (parallel/moe.py docstring).
    """
    import mpit_tpu
    from jax.sharding import PartitionSpec as P
    from mpit_tpu.data import SyntheticLM, shard_batch
    from mpit_tpu.models import GPT2Config
    from mpit_tpu.models.gpt2_moe import GPT2MoE, MoESettings
    from mpit_tpu.opt import goo_adam
    from mpit_tpu.parallel import make_gpt2_moe_train_step

    n = jax.device_count()
    world = mpit_tpu.init({"data": n, "expert": 1})
    batch = batch_per_device * n
    zero1 = True

    kw = dict(max_seq_len=seq, head_dtype=jnp.bfloat16)
    if jax.devices()[0].platform == "tpu" and seq >= 512:
        # Same rule as bench_gpt2: the Pallas flash kernel from T=512 up.
        # Round 5: without it the XLA attention saves [B,H,T,T] scores
        # for backward (~2.4 GB at B=32/T=512) — the other half of the
        # B=32 memory story next to the sort dispatch + expert remat.
        from mpit_tpu.ops import flash_attention

        kw["attention_fn"] = flash_attention
    cfg = GPT2Config.small(**kw)
    moe = MoESettings(num_experts=8, k=2, capacity_factor=1.25, every=2)
    model = GPT2MoE(cfg, moe)
    params = jax.jit(model.init)(
        jax.random.key(0), jnp.zeros((1, seq), jnp.int32)
    )["params"]

    init_fn, step_fn, _ = make_gpt2_moe_train_step(
        cfg, moe, goo_adam(3e-4), world, zero1=zero1
    )
    state = init_fn(params)
    stream = SyntheticLM(vocab_size=cfg.vocab_size).batches(batch, seq)
    batches = [
        shard_batch(world, next(stream), spec=P(("data", "expert")))
        for _ in range(2)
    ]
    # App-path measurement (one dispatch per step — the EP tier has no
    # scan chunking; the tier step is heavy enough to amortize the
    # tunnel's per-dispatch cost). Shared best-of-N scaffold, so the
    # methodology cannot drift between workloads.
    _, _, state = _timed_steps(step_fn, state, batches, 1)  # compile
    steps = 4
    dt, final_loss, state = _best_window(
        step_fn, state, batches, steps, repeats=max(calls - warmup, 1)
    )

    # Routing observability: drop rate / expert load on a probe forward
    # (mutable intermediates; never part of the timed window).
    probe = jnp.asarray(next(stream)["tokens"][: max(batch // 4, 1), :-1])
    probe_fn = jax.jit(
        lambda p, t: model.apply(
            {"params": p}, t, mutable=["intermediates"]
        )
    )

    def _drops(params):
        _, inter = probe_fn(params, probe)
        return [
            float(v)
            for k, v in jax.tree_util.tree_flatten_with_path(
                inter["intermediates"]
            )[0]
            if "drop_rate" in jax.tree_util.keystr(k) and v.ndim == 0
        ]

    drops = _drops(state.params)

    # Load-balance under training (ISSUE 3 satellite): keep training the
    # SAME state ~48 more steps, sampling the per-layer drop rate — the
    # aux loss should pull the random-init 36–64% down materially. Each
    # sample rides obs.gauge so the trajectory lands in the workload's
    # telemetry too; the list goes to BENCH_DETAIL.json (detail-only).
    from mpit_tpu import obs

    trajectory = [{"step": 0, "drop_rate_per_moe_layer":
                   [round(d, 4) for d in drops]}]
    probe_every, probe_steps = 12, 48
    with obs.span("moe_load_balance_probe", steps=probe_steps):
        for s in range(1, probe_steps + 1):
            state, _m = step_fn(state, batches[s % 2])
            if s % probe_every == 0:
                ds = _drops(state.params)
                for li, d in enumerate(ds):
                    obs.gauge("moe_drop_rate", d, layer=li, step=s)
                trajectory.append(
                    {"step": s,
                     "drop_rate_per_moe_layer": [round(d, 4) for d in ds]}
                )

    from mpit_tpu.utils import CommModel

    rb, mfu = _roofline_block(
        step_fn, (state, batches[0]), dt / steps,
        ici_bytes=CommModel(params, n, zero1=zero1).grad_sync_bytes(),
    )
    return {
        "tokens_per_sec": round(batch * seq * steps / dt, 1),
        "ms_per_step": round(dt / steps * 1e3, 2),
        "mfu_pct": mfu,
        "roofline": rb,
        "tier": "ep",
        "dispatch": moe.dispatch,
        "batch": batch,
        "seq_len": seq,
        "experts": moe.num_experts,
        "k": moe.k,
        "capacity_factor": moe.capacity_factor,
        "zero1": zero1,
        "drop_rate_per_moe_layer": [round(d, 4) for d in drops],
        "drop_rate_trajectory": trajectory,
        "final_loss": round(final_loss, 4),
        # The scaling block the round-5 verdict flagged as missing
        # (next-round #6): grad-sync model PLUS the expert all-to-all
        # (collective_bytes "alltoall" wired into scaling_projection).
        "scaling": _scaling(
            dt / steps, batch_per_device * seq, params,
            alltoall_payload_bytes=moe_alltoall_payload(
                cfg, moe, batch_per_device, seq
            ),
            alltoall_group=moe.num_experts,
            alltoall_passes=moe_alltoall_passes(cfg, moe),
        ),
    }


def _serve_stream(
    cfg, params, *, slots, max_len, prompt_len, max_new, requests,
    decode_attention, seed=0,
):
    """One measured request stream through a fresh engine: warmup runs
    ONE request first so the two compiles (prefill + decode — the
    engine's whole compiled surface) never land inside a measured
    request's TTFT/latency; then the engine resets (cache cleared,
    compiled steps kept) and the stream is measured cold-queue: all
    requests submitted up front, so queue-wait and slot-reuse are
    exercised (admissions > slots)."""
    import numpy as np

    from mpit_tpu import obs
    from mpit_tpu.serve import Engine, Request, Server, warm_engine

    engine = Engine(
        cfg, params, slots=slots, max_len=max_len, prefill_len=prompt_len,
        decode_attention=decode_attention,
    )
    rng = np.random.RandomState(seed)
    make_req = lambda i: Request(
        rid=i,
        prompt=rng.randint(0, cfg.vocab_size, size=prompt_len).tolist(),
        max_new_tokens=max_new,
    )
    # warm_engine spans itself as `warmup` (ISSUE 8 satellite) and
    # registers the steps' cost_analysis costs for the roofline roll-up.
    warm_engine(engine, register_costs=True)

    server = Server(engine)
    for i in range(requests):
        server.submit(make_req(i))
    rec = obs.get_recorder()
    n0 = rec.event_count() if rec else 0
    t0 = time.perf_counter()
    server.run()
    wall = time.perf_counter() - t0
    stats = server.stats()
    gen = stats["generated_tokens"]
    # Each request's FIRST token is sampled by prefill; only the rest are
    # decode-path work, so they alone ride the decode-phase denominator.
    decode_tokens = gen - stats["requests_completed"]
    decode_s = wall
    if rec is not None:
        phases = rec.summary(since=n0)["phases"]
        decode_s = phases.get("decode", {}).get("total_s", wall)
    return engine, stats, wall, decode_tokens, decode_s, gen


def _paged_capacity_block(page_size: int = 16):
    """Paged-vs-dense capacity at a FIXED HBM budget (ISSUE 7's pinned
    win). Budget = the dense engine's cache rows (``slots × max_len``);
    the paged pool gets exactly that many rows (``budget/page_size``
    pages) and a wide slot batch (batch width is host arrays + FLOPs,
    not HBM). The stream: page-aligned shared prefix + short tail, short
    generations — tokens actually held per request ≈ 28 of the dense
    path's 128-row reservation, so concurrency stops scaling with
    ``slots × max_len`` and starts scaling with tokens held (and shared
    prefix pages are stored once). Reports measured peak concurrency +
    decode tokens/s for both engines at identical traffic.
    """
    import numpy as np

    from mpit_tpu import obs
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.serve import Engine, Request, Server, warm_engine

    dense_slots, max_len = 4, 128
    budget_rows = dense_slots * max_len  # the HBM the dense cache burns
    num_pages = budget_rows // page_size
    paged_slots = dense_slots * 8
    prefix_len, tail, max_new = page_size, 4, 8
    n_requests = paged_slots + dense_slots * 4

    cfg = GPT2Config.tiny(max_seq_len=max_len)
    params = jax.jit(GPT2(cfg).init)(
        jax.random.key(2), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    rng = np.random.RandomState(3)
    prefix = rng.randint(0, cfg.vocab_size, size=prefix_len).tolist()
    reqs = [
        Request(
            rid=i,
            prompt=prefix
            + rng.randint(0, cfg.vocab_size, size=tail).tolist(),
            max_new_tokens=max_new,
        )
        for i in range(n_requests)
    ]

    def _measure(engine):
        warm_engine(engine)
        server = Server(engine)
        t0 = time.perf_counter()
        # Prime the prefix index before the wave: sharing requires a
        # REGISTERED prefix (registration happens when a prefill
        # completes — same-tick co-admissions are cold by design), so
        # the first request runs two ticks alone. The dense engine gets
        # the identical schedule, so the A/B traffic stays equal.
        server.submit(reqs[0])
        server.run(max_ticks=2)
        for r in reqs[1:]:
            server.submit(r)
        server.run()
        wall = time.perf_counter() - t0
        st = server.stats()
        dtok = st["generated_tokens"] - st["requests_completed"]
        return st, dtok / wall if wall else None

    with obs.span("paged_capacity"):
        d_stats, d_tps = _measure(
            Engine(cfg, params, slots=dense_slots, max_len=max_len,
                   prefill_len=prefix_len + tail)
        )
        p_stats, p_tps = _measure(
            Engine(cfg, params, slots=paged_slots, max_len=max_len,
                   prefill_len=prefix_len + tail,
                   kv_pages=num_pages, kv_page_size=page_size)
        )
    return {
        "hbm_budget_rows": budget_rows,
        "page_size": page_size,
        "request_shape": {"prefix_len": prefix_len, "tail": tail,
                          "max_new": max_new, "requests": n_requests},
        "dense": {
            "slots": dense_slots,
            "max_concurrent": d_stats["concurrency_peak"],
            "decode_tokens_per_sec": round(d_tps, 1) if d_tps else None,
        },
        "paged": {
            "slots": paged_slots,
            "pages": num_pages,
            "max_concurrent": p_stats["concurrency_peak"],
            "decode_tokens_per_sec": round(p_tps, 1) if p_tps else None,
            "pool_occupancy_peak": p_stats["kv_pool_occupancy_peak"],
            "prefix_hit_rate": p_stats["prefix_hit_rate"],
            "pages_shared_peak": p_stats["prefix_pages_shared_peak"],
            "cow_copies": p_stats["kv_cow_copies"],
        },
        "concurrency_ratio": round(
            p_stats["concurrency_peak"]
            / max(d_stats["concurrency_peak"], 1),
            2,
        ),
    }


def _chunked_prefill_block(prefill_chunk: int = 32):
    """Chunked-prefill TTFT under the mixed-length open-loop harness
    (ISSUE 7): the SAME seeded arrival trace (80% short interactive
    prompts, 20% long batch prompts) driven through the paged engine
    with whole-prompt prefills vs ``prefill_chunk``-token slices.

    The long admits are what head-of-line-blocks INTERACTIVE TTFT;
    chunking bounds any tick's prefill work, so the interactive class's
    p95 TTFT is the headline improvement. The long requests' own TTFT
    rises (their prompt now lands over several ticks with decode
    interleaved — that is the trade chunking makes, and why overall
    p95, which sits inside the 20% long class, can move the other way);
    both classes' percentiles are recorded so the trade is explicit.
    """
    import numpy as np

    from mpit_tpu import obs
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.serve import (
        Engine,
        LoadSpec,
        RequestClass,
        Server,
        generate_arrivals,
        warm_engine,
    )

    prefill_len, max_len = 256, 320
    cfg = GPT2Config.tiny(max_seq_len=max_len)
    params = jax.jit(GPT2(cfg).init)(
        jax.random.key(4), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mix = (
        RequestClass("interactive", weight=0.8, prompt_len=(2, 10),
                     max_new_tokens=(2, 6)),
        RequestClass("batch", weight=0.2,
                     prompt_len=(prefill_len - 64, prefill_len),
                     max_new_tokens=(2, 6)),
    )
    duration = 2.5

    def _measure(chunk):
        engine = Engine(
            cfg, params, slots=4, max_len=max_len,
            prefill_len=prefill_len, kv_pages=96, kv_page_size=16,
            prefill_chunk=chunk,
        )
        warm_engine(engine)
        # Rate calibrated roughly to CPU tiny-model tick cost; the A/B
        # shares ONE trace, so the absolute rate only sets pressure.
        arrivals = generate_arrivals(
            LoadSpec(rate=14.0, classes=mix),
            vocab_size=cfg.vocab_size, duration_s=duration, seed=11,
        )
        server = Server(engine)
        server.run_timed(arrivals, duration=duration, drain=True)
        by_class = {a.request.rid: a.klass for a in arrivals}
        ttft = np.asarray([c.ttft_s for c in server.completed])
        inter = np.asarray(
            [c.ttft_s for c in server.completed
             if by_class[c.rid] == "interactive"]
        )
        batch_t = np.asarray(
            [c.ttft_s for c in server.completed
             if by_class[c.rid] == "batch"]
        )
        pct = lambda a, q: (
            round(float(np.percentile(a, q)), 6) if a.size else None
        )
        return {
            "completed": len(server.completed),
            "ttft_p50_s": pct(ttft, 50),
            "ttft_p95_s": pct(ttft, 95),
            "interactive_ttft_p50_s": pct(inter, 50),
            "interactive_ttft_p95_s": pct(inter, 95),
            "batch_ttft_p95_s": pct(batch_t, 95),
        }

    with obs.span("chunked_prefill_ab"):
        unchunked = _measure(None)
        chunked = _measure(prefill_chunk)
    u, c = (unchunked["interactive_ttft_p95_s"],
            chunked["interactive_ttft_p95_s"])
    imp = (u - c) / u if u and c is not None else None
    return {
        "geometry": {"slots": 4, "prefill_len": prefill_len,
                     "prefill_chunk": prefill_chunk, "kv_pages": 96,
                     "kv_page_size": 16, "duration_s": duration,
                     "rate": 14.0},
        "unchunked": unchunked,
        "chunked": chunked,
        "interactive_ttft_p95_improvement_pct": round(100 * imp, 1)
        if imp is not None
        else None,
    }


def _speculative_block(
    spec_k: int = 3, draft_layers: int = 1, contexts: tuple = (16, 48),
    train_steps: int = 300,
):
    """Speculative-decode A/B (ISSUE 13): the SAME seeded request trace
    through the same engine geometry, spec on vs off, at acceptance
    rates the trace ACTUALLY ACHIEVES — both ends of the bracket:

    - ``trained``: target (4 layers) and draft (``draft_layers``)
      trained to convergence on a memorizable synthetic stream, the
      regime speculation exists for (the draft genuinely predicts the
      target — greedy continuations agree, acceptance is high, and the
      tokens/s improvement is real);
    - ``random_draft``: the same geometry with a random-init target and
      its layer-truncated self-draft (``serve.weights.
      draft_from_target``) — the floor: near-zero acceptance, so every
      tick pays draft + verify for ~1 token and speculation LOSES.
      Recording the loss is the point; a draft that cannot predict the
      target should never be shipped, and the bench must say what that
      costs rather than hide it.

    On CPU these are acceptance/tokens-per-tick/relative-cost facts
    with honest wall clocks — never a chip-speedup claim (the record's
    top-level platform label governs, per BENCHMARKS.md discipline).
    Reduced geometry (vocab 256, d_model 128) keeps the block inside
    the bench budget; the A/B signal is relative cost at achieved
    acceptance, not an absolute rate — geometry rides the entry."""
    import dataclasses

    import numpy as np
    import optax

    from mpit_tpu import obs
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.opt.goo import goo_adam
    from mpit_tpu.serve import (
        Engine,
        Request,
        Server,
        draft_from_target,
        warm_engine,
    )

    cfg = GPT2Config(
        vocab_size=256, max_seq_len=128, num_layers=4, num_heads=4,
        d_model=128, head_dtype=jnp.bfloat16,
    )
    dcfg = dataclasses.replace(cfg, num_layers=draft_layers)
    slots, max_new, requests = 4, 12, 8
    rng = np.random.RandomState(17)
    # The memorizable stream: one fixed token sequence; every prompt is
    # a prefix of it, so the trained pair's greedy continuations are
    # the stream itself — the high-agreement regime.
    stream = rng.randint(0, cfg.vocab_size, size=96).tolist()
    batch = jnp.asarray([stream[:65]], jnp.int32)

    def _train(mcfg, seed):
        model = GPT2(mcfg)
        params = jax.jit(model.init)(
            jax.random.key(seed), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        opt = goo_adam(3e-3)
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            loss, grads = jax.value_and_grad(
                lambda p: GPT2.fused_loss_fn(model, p, batch)
            )(params)
            updates, state = opt.update(grads, state, params)
            return optax.apply_updates(params, updates), state, loss

        loss = None
        for _ in range(train_steps):
            params, state, loss = step(params, state)
        return params, float(loss)

    rec = obs.get_recorder()

    def _measure_pair(tparams, dparams, draft_cfg):
        plain = Engine(cfg, tparams, slots=slots, max_len=128,
                       prefill_len=max(contexts))
        spec = Engine(cfg, tparams, slots=slots, max_len=128,
                      prefill_len=max(contexts), spec_k=spec_k,
                      draft_params=dparams, draft_cfg=draft_cfg)
        warm_engine(plain)
        warm_engine(spec)

        def _stream_run(engine, ctx):
            engine.reset()
            server = Server(engine)
            for i in range(requests):
                plen = ctx - (i % 3)  # same trace both ways, mild skew
                server.submit(Request(
                    rid=i, prompt=stream[:plen], max_new_tokens=max_new,
                ))
            n0 = rec.event_count() if rec else 0
            t0 = time.perf_counter()
            server.run()
            wall = time.perf_counter() - t0
            st = server.stats()
            dtok = st["generated_tokens"] - st["requests_completed"]
            ds = wall
            if rec is not None:
                ph = rec.summary(since=n0)["phases"]
                ds = ph.get("decode", {}).get("total_s", wall)
            return st, (dtok / ds if ds else None)

        points = []
        for ctx in contexts:
            p_st, p_tps = _stream_run(plain, ctx)
            s_st, s_tps = _stream_run(spec, ctx)
            points.append({
                "context_len": ctx,
                "decode_tokens_per_sec": (
                    round(p_tps, 1) if p_tps else None
                ),
                "spec_decode_tokens_per_sec": (
                    round(s_tps, 1) if s_tps else None
                ),
                "spec_speedup": (
                    round(s_tps / p_tps, 3) if p_tps and s_tps else None
                ),
                "accepted_tokens_per_tick": s_st.get(
                    "accepted_tokens_per_tick"
                ),
                "draft_acceptance_rate": s_st.get(
                    "draft_acceptance_rate"
                ),
                "ttft_p95_delta_s": (
                    round(s_st["ttft_p95_s"] - p_st["ttft_p95_s"], 6)
                    if "ttft_p95_s" in s_st and "ttft_p95_s" in p_st
                    else None
                ),
            })
        return points

    with obs.span("speculative_ab"):
        tparams, t_loss = _train(cfg, seed=5)
        dparams_t, d_loss = _train(dcfg, seed=6)
        trained_points = _measure_pair(tparams, dparams_t, dcfg)
        rnd = jax.jit(GPT2(cfg).init)(
            jax.random.key(7), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        rnd_draft, rnd_dcfg = draft_from_target(rnd, cfg, draft_layers)
        random_points = _measure_pair(rnd, rnd_draft, rnd_dcfg)
    att = [p["accepted_tokens_per_tick"] for p in trained_points
           if p["accepted_tokens_per_tick"] is not None]
    return {
        "geometry": dict(
            vocab=cfg.vocab_size, d_model=cfg.d_model,
            num_layers=cfg.num_layers, slots=slots, max_len=128,
            max_new=max_new, requests=requests, spec_k=spec_k,
            draft_layers=draft_layers, train_steps=train_steps,
        ),
        "trained": {
            "target_final_loss": round(t_loss, 4),
            "draft_final_loss": round(d_loss, 4),
            "points": trained_points,
        },
        "random_draft": {"points": random_points},
        "accepted_tokens_per_tick": (
            round(sum(att) / len(att), 4) if att else None
        ),
    }


def _train_tiny_lm(mcfg, batch, train_steps: int, seed: int):
    """Memorize ``batch`` on a fresh tiny GPT-2 — the trained-checkpoint
    regime the quantized-cache/weights quality gates run in (a random
    init would make every agreement gate vacuous). Shared by the
    ISSUE 15 KV block and the ISSUE 17 weights block so the two
    batteries gate the same kind of checkpoint. Returns
    ``(params, final_loss)``."""
    import optax

    from mpit_tpu.models import GPT2
    from mpit_tpu.opt.goo import goo_adam

    model = GPT2(mcfg)
    params = jax.jit(model.init)(
        jax.random.key(seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    opt = goo_adam(3e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(
            lambda p: GPT2.fused_loss_fn(model, p, batch)
        )(params)
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state, loss

    loss = None
    for _ in range(train_steps):
        params, state, loss = step(params, state)
    return params, float(loss)


def _greedy_stream_run(engine, rec, stream_toks, slots, prompt_len,
                       max_new):
    """One seeded greedy trace: prompts are prefixes of the memorized
    stream (mild length skew), one warm + measured run. Returns
    ``(stats, decode_tokens_per_sec, {rid: tokens})`` — decode tok/s
    from the recorder's decode-phase seconds when available (whole-run
    wall otherwise)."""
    from mpit_tpu.serve import Request, Server, warm_engine

    warm_engine(engine)
    server = Server(engine)
    for i in range(slots):
        plen = prompt_len - (i % 3)
        server.submit(Request(
            rid=i, prompt=stream_toks[:plen], max_new_tokens=max_new,
        ))
    n0 = rec.event_count() if rec else 0
    t0 = time.perf_counter()
    server.run()
    wall = time.perf_counter() - t0
    st = server.stats()
    dtok = st["generated_tokens"] - st["requests_completed"]
    ds = wall
    if rec is not None:
        ph = rec.summary(since=n0)["phases"]
        ds = ph.get("decode", {}).get("total_s", wall)
    outs = {c.rid: c.tokens for c in server.completed}
    return st, (dtok / ds if ds else None), outs


def _quantized_kv_block(train_steps: int = 300, page_size: int = 16):
    """Quantized int8 KV cache A/B + capacity sweep + quality gates
    (ISSUE 15). One head_dim-64 config (the GPT-2 head geometry — the
    byte-ratio claims are head_dim-dependent) serves four sub-blocks:

    - ``ab``: the SAME seeded stream through identical paged engines at
      kv_dtype bf16 vs int8 — measured decode tokens/s (CPU wall,
      platform-labeled, never a chip claim) plus the MODELED
      bytes-per-tick ratios at the stream's lengths: the KV-sweep-only
      ratio (``q8_kv_sweep_ratio`` — the term quantization shrinks;
      int8+scales vs bf16 rows at identical visited tiles) and the
      total ratio including the dtype-independent param read, recorded
      next to it so the tiny-model param share is explicit, not hidden.
    - ``capacity``: the SAME pool HBM byte budget spent on bf16 pages
      vs int8 pages (page counts from the shared
      ``kv_wire_bytes_per_row`` sizing rule), identical traffic —
      measured peak concurrency both ways; ``q8_capacity_ratio`` is
      the headline (admission granularity means the measured ratio can
      sit above the raw row-bytes ratio; both are recorded).
    - ``quality``: gates on a TRAINED checkpoint (the regime a serving
      cache lives in), deltas recorded not assumed — max per-token
      logit error of the int8 cache vs the f32-cache oracle (+ its
      anti-vacuity twin: the error must be nonzero, lossy must
      actually execute), and greedy-output agreement vs the f32-cache
      engine over the stream (bf16 agreement alongside as context).
    - ``speculative``: acceptance-rate neutrality — the trained target
      + its layer-truncated draft, spec_k=3, quantized both pools vs
      unquantized; the acceptance delta is the recorded gate.
    """
    import numpy as np

    from mpit_tpu import obs
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.serve import (
        Engine,
        Request,
        Server,
        alloc_cache,
        draft_from_target,
        kv_wire_bytes_per_row,
        warm_engine,
    )

    cfg = GPT2Config(
        vocab_size=256, max_seq_len=192, num_layers=2, num_heads=4,
        d_model=256, head_dtype=jnp.bfloat16,
    )
    slots, prompt_len, max_new, max_len = 8, 64, 16, 96
    rng = np.random.RandomState(23)
    stream_toks = rng.randint(0, cfg.vocab_size, size=160).tolist()
    batch = jnp.asarray([stream_toks[:129]], jnp.int32)

    rec = obs.get_recorder()

    def _stream_run(engine):
        return _greedy_stream_run(
            engine, rec, stream_toks, slots, prompt_len, max_new
        )

    def _paged(params, kv_dtype, pages, n_slots=slots):
        return Engine(
            cfg, params, slots=n_slots, max_len=max_len,
            prefill_len=prompt_len, kv_pages=pages,
            kv_page_size=page_size, kv_dtype=kv_dtype,
        )

    with obs.span("quantized_kv_ab"):
        tparams, t_loss = _train_tiny_lm(cfg, batch, train_steps, seed=5)

        # -- A/B at identical geometry --------------------------------------
        pages_ab = slots * (max_len // page_size)
        ab = {}
        engines = {}
        for dt in ("f32", "bf16", "int8"):
            eng = _paged(tparams, dt, pages_ab)
            st, tps, outs = _stream_run(eng)
            engines[dt] = (eng, outs)
            ab[dt] = {
                "decode_tokens_per_sec": round(tps, 1) if tps else None,
                "decode_hbm_bytes_modeled": st.get(
                    "decode_hbm_bytes_modeled"
                ),
            }
        # Modeled bytes at the stream's lengths (deterministic: every
        # engine ran the same schedule): one representative tick with
        # all slots at their final fills, KV sweep only vs total.
        lens = np.asarray(
            [prompt_len - (i % 3) + max_new - 1 for i in range(slots)]
        )
        kv_only = {
            dt: engines[dt][0].decode_achieved_hbm_bytes(
                lens, include_params=False
            )
            for dt in engines
        }
        total = {
            dt: engines[dt][0].decode_achieved_hbm_bytes(lens)
            for dt in engines
        }
        ab["q8_kv_sweep_ratio_vs_bf16"] = round(
            kv_only["int8"] / kv_only["bf16"], 4
        )
        ab["q8_kv_sweep_ratio_vs_f32"] = round(
            kv_only["int8"] / kv_only["f32"], 4
        )
        # The tiny bench model's param read dominates a CPU-sized tick;
        # the total ratio records that share honestly instead of letting
        # the sweep ratio imply a whole-tick 2x on this geometry.
        ab["q8_total_bytes_ratio_vs_bf16"] = round(
            total["int8"] / total["bf16"], 4
        )
        ab["kv_row_bytes"] = {
            dt: kv_wire_bytes_per_row(
                cfg.num_heads, cfg.head_dim,
                "int8" if dt == "int8" else
                (jnp.float32 if dt == "f32" else jnp.bfloat16),
            )
            for dt in ("f32", "bf16", "int8")
        }

        # -- capacity at a FIXED pool HBM budget ----------------------------
        row = ab["kv_row_bytes"]
        pages_bf16 = 24
        budget_bytes = pages_bf16 * page_size * row["bf16"]
        pages_int8 = int(budget_bytes // (page_size * row["int8"]))
        cap_slots, cap_requests = 16, 30
        crng = np.random.RandomState(29)
        cap_reqs = [
            Request(
                rid=i,
                prompt=crng.randint(
                    0, cfg.vocab_size, size=prompt_len
                ).tolist(),
                max_new_tokens=max_new,
            )
            for i in range(cap_requests)
        ]

        def _capacity(kv_dtype, pages):
            eng = _paged(tparams, kv_dtype, pages, n_slots=cap_slots)
            warm_engine(eng)
            server = Server(eng)
            for r in cap_reqs:
                server.submit(r)
            t0 = time.perf_counter()
            server.run()
            wall = time.perf_counter() - t0
            st = server.stats()
            dtok = st["generated_tokens"] - st["requests_completed"]
            return {
                "pages": pages,
                "max_concurrent": st["concurrency_peak"],
                "pool_occupancy_peak": st["kv_pool_occupancy_peak"],
                "decode_tokens_per_sec": (
                    round(dtok / wall, 1) if wall else None
                ),
            }

        cap_bf = _capacity("bf16", pages_bf16)
        cap_i8 = _capacity("int8", pages_int8)
        capacity = {
            "pool_budget_bytes": int(budget_bytes),
            "page_size": page_size,
            "request_shape": {
                "prompt_len": prompt_len, "max_new": max_new,
                "pages_per_request": -(-(prompt_len + max_new - 1)
                                       // page_size),
                "requests": cap_requests, "slots": cap_slots,
            },
            "bf16": cap_bf,
            "int8": cap_i8,
            # Measured-concurrency ratio; the raw row-bytes ratio sits
            # beside it (admission is page-granular, so the measured
            # figure can exceed it — both recorded, neither fabricated).
            "q8_capacity_ratio": round(
                cap_i8["max_concurrent"] / max(cap_bf["max_concurrent"], 1),
                2,
            ),
            "row_bytes_ratio_bf16_over_int8": round(
                row["bf16"] / row["int8"], 4
            ),
        }

        # -- quality gates on the trained checkpoint ------------------------
        # Per-token logit error vs the f32-cache oracle: one padded
        # prefill over stream prefixes through an f32 cache and an int8
        # cache, same params, logits compared at every real position.
        model = GPT2(cfg)
        q_slots, q_len = 4, prompt_len
        padded = np.zeros((q_slots, q_len), np.int32)
        for i in range(q_slots):
            padded[i, : q_len - i] = stream_toks[: q_len - i]
        c_f32 = alloc_cache(cfg, slots=q_slots, max_len=q_len,
                            dtype=jnp.float32)
        c_i8 = alloc_cache(cfg, slots=q_slots, max_len=q_len,
                           quantized=True)
        lf, _ = model.apply(
            {"params": tparams}, jnp.asarray(padded),
            cache=(c_f32.k, c_f32.v, c_f32.lengths),
        )
        lq, _ = model.apply(
            {"params": tparams}, jnp.asarray(padded),
            cache=(c_i8.k, c_i8.v, c_i8.lengths),
        )
        # Positional mask: row i holds q_len - i real tokens. (Token id
        # 0 is a valid vocab id — a value mask would silently drop the
        # real positions holding it from the error measurement.)
        mask = (
            np.arange(q_len)[None, :]
            < (q_len - np.arange(q_slots))[:, None]
        )
        delta = np.abs(np.asarray(lf, np.float32)
                       - np.asarray(lq, np.float32))[mask]
        agree = {}
        f32_outs = engines["f32"][1]
        for dt in ("bf16", "int8"):
            outs = engines[dt][1]
            same = sum(
                t == r
                for rid in f32_outs
                for t, r in zip(outs[rid], f32_outs[rid])
            )
            total_toks = sum(len(v) for v in f32_outs.values())
            agree[dt] = round(same / total_toks, 4)
        quality = {
            "target_final_loss": round(t_loss, 4),
            "logit_abs_err_max": round(float(delta.max()), 5),
            "logit_abs_err_mean": round(float(delta.mean()), 6),
            # Anti-vacuity: zero error would mean the lossy path never
            # executed — the gates below would be vacuously green.
            "logit_err_nonzero": bool(delta.max() > 0),
            "greedy_agreement_vs_f32": agree,
        }

        # -- speculative acceptance neutrality ------------------------------
        dparams, dcfg = draft_from_target(tparams, cfg, 1)
        spec_acc = {}
        for dt in (None, "int8"):
            eng = Engine(
                cfg, tparams, slots=slots, max_len=128,
                prefill_len=prompt_len, spec_k=3,
                draft_params=dparams, draft_cfg=dcfg, kv_dtype=dt,
            )
            st, _tps, _outs = _stream_run(eng)
            spec_acc[dt or "bf16"] = {
                "draft_acceptance_rate": st.get("draft_acceptance_rate"),
                "accepted_tokens_per_tick": st.get(
                    "accepted_tokens_per_tick"
                ),
            }
        a0 = spec_acc["bf16"]["draft_acceptance_rate"]
        a8 = spec_acc["int8"]["draft_acceptance_rate"]
        spec = {
            **spec_acc,
            "acceptance_delta": (
                round(a8 - a0, 4) if a0 is not None and a8 is not None
                else None
            ),
        }

    return {
        "geometry": dict(
            vocab=cfg.vocab_size, d_model=cfg.d_model,
            num_layers=cfg.num_layers, num_heads=cfg.num_heads,
            head_dim=cfg.head_dim, slots=slots, max_len=max_len,
            prompt_len=prompt_len, max_new=max_new,
            page_size=page_size, train_steps=train_steps,
        ),
        "ab": ab,
        "capacity": capacity,
        "quality": quality,
        "speculative_neutrality": spec,
        "q8_capacity_ratio": capacity["q8_capacity_ratio"],
        "q8_kv_sweep_ratio": ab["q8_kv_sweep_ratio_vs_bf16"],
    }


def _quantized_weights_block(train_steps: int = 300, page_size: int = 16):
    """Quantized int8 weight store A/B + capacity + quality gates
    (ISSUE 17). The KV block's honesty note is this block's premise: at
    serving batch sizes the PARAM read dominates the decode tick
    (``q8_total_bytes_ratio_vs_bf16`` ≈ 0.92 — the cache is the
    sliver), so the weights are where the bytes are. Four sub-blocks on
    one trained checkpoint:

    - ``ab``: the SAME seeded stream through identical dense engines at
      weights_dtype f32 vs int8 — measured decode tokens/s (CPU wall,
      platform-labeled, never a chip claim) plus the MODELED whole-tick
      decode-bytes ratio at the stream's lengths (``q8w_bytes_ratio``,
      the record-line headline: param read + KV sweep, each at its
      actual wire dtype — the ratio credits quantization with exactly
      the term it shrinks, diluted by the sweep it does not touch) and
      the param-read / wire ratios from the shared
      ``weight_wire_bytes`` sizing rule.
    - ``capacity``: the SAME total HBM budget (param store + KV pool)
      spent with f32 vs int8 weights — freed param bytes convert to KV
      pages; measured peak concurrency both ways. On this tiny geometry
      the int8 page grant is slot-capped; the uncapped modeled grant is
      recorded next to the granted one — neither fabricated.
    - ``quality``: gates on the TRAINED checkpoint — max per-token
      logit error of the int8-weight forward vs the f32-weight oracle
      through the SAME f32 cache (+ anti-vacuity: the error must be
      nonzero, the lossy path must actually execute), and greedy
      agreement vs the f32-weight engine over the stream.
    - ``speculative``: acceptance neutrality with int8 weights on BOTH
      draft and target (the engine quantizes the draft store too) vs
      the unquantized pair; the acceptance delta is the recorded gate.
    """
    import numpy as np

    from mpit_tpu import obs
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.serve import (
        Engine,
        Request,
        Server,
        alloc_cache,
        draft_from_target,
        kv_wire_bytes_per_row,
        params_wire_bytes,
        quantize_gpt2_params,
        warm_engine,
    )

    cfg = GPT2Config(
        vocab_size=256, max_seq_len=192, num_layers=2, num_heads=4,
        d_model=256, head_dtype=jnp.bfloat16,
    )
    slots, prompt_len, max_new, max_len = 8, 64, 16, 96
    rng = np.random.RandomState(31)
    stream_toks = rng.randint(0, cfg.vocab_size, size=160).tolist()
    batch = jnp.asarray([stream_toks[:129]], jnp.int32)
    rec = obs.get_recorder()

    def _stream_run(engine):
        return _greedy_stream_run(
            engine, rec, stream_toks, slots, prompt_len, max_new
        )

    with obs.span("quantized_weights_ab"):
        tparams, t_loss = _train_tiny_lm(cfg, batch, train_steps, seed=7)
        # The shared sizing rule (``weight_wire_bytes`` under
        # ``params_wire_bytes``): what each param store occupies on the
        # wire — int8 payload + per-row f32 scales vs dense f32.
        pw = {
            "f32": params_wire_bytes(tparams),
            "int8": params_wire_bytes(quantize_gpt2_params(tparams)),
        }

        # -- A/B at identical geometry --------------------------------------
        ab = {}
        engines = {}
        for dt in ("f32", "int8"):
            eng = Engine(
                cfg, tparams, slots=slots, max_len=max_len,
                prefill_len=prompt_len, weights_dtype=dt,
            )
            st, tps, outs = _stream_run(eng)
            engines[dt] = (eng, outs)
            ab[dt] = {
                "decode_tokens_per_sec": round(tps, 1) if tps else None,
                "decode_hbm_bytes_modeled": st.get(
                    "decode_hbm_bytes_modeled"
                ),
                "param_wire_bytes": pw[dt],
            }
        # Modeled bytes for one representative tick (all slots at their
        # final fills — deterministic, every engine ran the same
        # schedule): whole tick and the param read it contains.
        lens = np.asarray(
            [prompt_len - (i % 3) + max_new - 1 for i in range(slots)]
        )
        total = {
            dt: engines[dt][0].decode_achieved_hbm_bytes(lens)
            for dt in engines
        }
        kv_sweep = {
            dt: engines[dt][0].decode_achieved_hbm_bytes(
                lens, include_params=False
            )
            for dt in engines
        }
        param_read = {dt: total[dt] - kv_sweep[dt] for dt in engines}
        ab["q8w_bytes_ratio"] = round(total["int8"] / total["f32"], 4)
        ab["q8w_param_read_ratio"] = round(
            param_read["int8"] / param_read["f32"], 4
        )
        ab["param_wire_ratio"] = round(pw["int8"] / pw["f32"], 4)
        # The KV block's honesty note, inverted: how much of the f32
        # tick the param read IS on this geometry — here the dominant
        # term is the one being shrunk.
        ab["param_share_of_f32_tick"] = round(
            param_read["f32"] / total["f32"], 4
        )

        # -- capacity at a FIXED total HBM budget (params + pool) -----------
        # The KV block holds the POOL budget fixed; here the budget
        # covers the param store too — the bytes weight quantization
        # frees are real HBM that converts to KV pages.
        row = kv_wire_bytes_per_row(
            cfg.num_heads, cfg.head_dim, jnp.bfloat16
        )
        page_bytes = 2 * cfg.num_layers * page_size * row  # K+V, all layers
        pages_per_req = -(-(prompt_len + max_new - 1) // page_size)
        pages_f32 = 3 * pages_per_req  # the f32 arm: 3 requests' worth
        budget_bytes = pw["f32"] + pages_f32 * page_bytes
        pages_int8_modeled = int(
            (budget_bytes - pw["int8"]) // page_bytes
        )
        cap_slots, cap_requests = 12, 24
        # The modeled grant dwarfs what the slot batch can touch on this
        # tiny geometry (params >> pool) — grant what the slots can use
        # and record BOTH numbers.
        pages_int8 = min(pages_int8_modeled, cap_slots * pages_per_req)
        crng = np.random.RandomState(37)
        cap_reqs = [
            Request(
                rid=i,
                prompt=crng.randint(
                    0, cfg.vocab_size, size=prompt_len
                ).tolist(),
                max_new_tokens=max_new,
            )
            for i in range(cap_requests)
        ]

        def _capacity(weights_dtype, pages):
            eng = Engine(
                cfg, tparams, slots=cap_slots, max_len=max_len,
                prefill_len=prompt_len, kv_pages=pages,
                kv_page_size=page_size, kv_dtype="bf16",
                weights_dtype=weights_dtype,
            )
            warm_engine(eng)
            server = Server(eng)
            for r in cap_reqs:
                server.submit(r)
            t0 = time.perf_counter()
            server.run()
            wall = time.perf_counter() - t0
            st = server.stats()
            dtok = st["generated_tokens"] - st["requests_completed"]
            return {
                "pages": pages,
                "param_wire_bytes": pw[weights_dtype],
                "max_concurrent": st["concurrency_peak"],
                "pool_occupancy_peak": st["kv_pool_occupancy_peak"],
                "decode_tokens_per_sec": (
                    round(dtok / wall, 1) if wall else None
                ),
            }

        cap_f32 = _capacity("f32", pages_f32)
        cap_i8 = _capacity("int8", pages_int8)
        capacity = {
            "total_budget_bytes": int(budget_bytes),
            "page_bytes": int(page_bytes),
            "page_size": page_size,
            "request_shape": {
                "prompt_len": prompt_len, "max_new": max_new,
                "pages_per_request": pages_per_req,
                "requests": cap_requests, "slots": cap_slots,
            },
            "f32": cap_f32,
            "int8": cap_i8,
            "pages_int8_modeled": pages_int8_modeled,
            "int8_pages_slot_capped": pages_int8 < pages_int8_modeled,
            "q8w_capacity_ratio": round(
                cap_i8["max_concurrent"]
                / max(cap_f32["max_concurrent"], 1),
                2,
            ),
        }

        # -- quality gates on the trained checkpoint ------------------------
        # Same f32 cache BOTH sides — only the weight store differs, so
        # the delta is weight quantization and nothing else.
        model = GPT2(cfg)
        qparams = quantize_gpt2_params(tparams)
        q_slots, q_len = 4, prompt_len
        padded = np.zeros((q_slots, q_len), np.int32)
        for i in range(q_slots):
            padded[i, : q_len - i] = stream_toks[: q_len - i]
        c_f = alloc_cache(cfg, slots=q_slots, max_len=q_len,
                          dtype=jnp.float32)
        c_q = alloc_cache(cfg, slots=q_slots, max_len=q_len,
                          dtype=jnp.float32)
        lf, _ = model.apply(
            {"params": tparams}, jnp.asarray(padded),
            cache=(c_f.k, c_f.v, c_f.lengths),
        )
        lq, _ = model.apply(
            {"params": qparams}, jnp.asarray(padded),
            cache=(c_q.k, c_q.v, c_q.lengths),
        )
        # Positional mask: row i holds q_len - i real tokens (a value
        # mask would drop real positions holding token id 0).
        mask = (
            np.arange(q_len)[None, :]
            < (q_len - np.arange(q_slots))[:, None]
        )
        delta = np.abs(np.asarray(lf, np.float32)
                       - np.asarray(lq, np.float32))[mask]
        f32_outs = engines["f32"][1]
        i8_outs = engines["int8"][1]
        same = sum(
            t == r
            for rid in f32_outs
            for t, r in zip(i8_outs[rid], f32_outs[rid])
        )
        total_toks = sum(len(v) for v in f32_outs.values())
        quality = {
            "target_final_loss": round(t_loss, 4),
            "logit_abs_err_max": round(float(delta.max()), 5),
            "logit_abs_err_mean": round(float(delta.mean()), 6),
            # Anti-vacuity: zero error would mean the quantized store
            # never fed a matmul — the gates would be vacuously green.
            "logit_err_nonzero": bool(delta.max() > 0),
            "greedy_agreement_vs_f32": round(same / total_toks, 4),
        }

        # -- speculative acceptance neutrality ------------------------------
        # int8 weights go on BOTH draft and target (the engine
        # quantizes the draft store too) — acceptance compares two
        # quantized models against each other, the deployed shape.
        dparams, dcfg = draft_from_target(tparams, cfg, 1)
        spec_acc = {}
        for dt in ("f32", "int8"):
            eng = Engine(
                cfg, tparams, slots=slots, max_len=128,
                prefill_len=prompt_len, spec_k=3,
                draft_params=dparams, draft_cfg=dcfg,
                weights_dtype=dt,
            )
            st, _tps, _outs = _stream_run(eng)
            spec_acc[dt] = {
                "draft_acceptance_rate": st.get("draft_acceptance_rate"),
                "accepted_tokens_per_tick": st.get(
                    "accepted_tokens_per_tick"
                ),
            }
        a0 = spec_acc["f32"]["draft_acceptance_rate"]
        a8 = spec_acc["int8"]["draft_acceptance_rate"]
        spec = {
            **spec_acc,
            "acceptance_delta": (
                round(a8 - a0, 4) if a0 is not None and a8 is not None
                else None
            ),
        }

    return {
        "geometry": dict(
            vocab=cfg.vocab_size, d_model=cfg.d_model,
            num_layers=cfg.num_layers, num_heads=cfg.num_heads,
            head_dim=cfg.head_dim, slots=slots, max_len=max_len,
            prompt_len=prompt_len, max_new=max_new,
            page_size=page_size, train_steps=train_steps,
        ),
        "ab": ab,
        "capacity": capacity,
        "quality": quality,
        "speculative_neutrality": spec,
        "q8w_bytes_ratio": ab["q8w_bytes_ratio"],
        "q8w_capacity_ratio": capacity["q8w_capacity_ratio"],
    }


def _trace_forensics_block(
    requests: int = 24, max_new: int = 16, reps: int = 3,
):
    """The request-ledger overhead A/B + forensics snapshot (ISSUE 16).

    Deliberately a TINY-geometry paged engine, not the headline one:
    the ledger's per-event cost is engine-independent (a dict append on
    the host), so millisecond decode ticks make it proportionally
    LARGEST here — the recorded pct is an honest upper bound for the
    production config, measured where the statistics are good instead
    of drowned in a 100ms-tick stream's wall-clock noise. Three arms
    (ledger off / aggregate-only counters / full exemplar capture) on
    identical seeded streams, alternated ``reps`` times, best (min
    decode seconds) per arm — the standard best-of-N noise floor.
    ``trace_overhead_pct`` is the aggregate arm (the always-on
    production configuration; acceptance wants <1% — recorded, never
    asserted here: wall-clock honesty). The full arm's snapshot IS the
    forensics evidence: ``why-slow`` must exit 0 on this BENCH_DETAIL
    block, which ties the CLI's input contract to a real bench run.
    """
    import numpy as np

    from mpit_tpu import obs
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.obs.trace import Ledger
    from mpit_tpu.serve import Engine, Request, Server, warm_engine

    cfg = GPT2Config.tiny(max_seq_len=64)
    params = jax.jit(GPT2(cfg).init)(
        jax.random.key(2), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine = Engine(
        cfg, params, slots=4, max_len=64, prefill_len=32,
        kv_pages=32, kv_page_size=8, prefill_chunk=8,
    )
    warm_engine(engine)

    def _run(ledger):
        engine.reset()
        rng = np.random.RandomState(5)
        server = Server(engine, ledger=ledger)
        for i in range(requests):
            plen = int(rng.randint(4, 28))
            server.submit(Request(
                rid=f"t{i}",
                prompt=rng.randint(0, cfg.vocab_size, size=plen).tolist(),
                max_new_tokens=max_new,
            ))
        rec = obs.get_recorder()
        n0 = rec.event_count() if rec else 0
        t0 = time.perf_counter()
        server.run()
        wall = time.perf_counter() - t0
        stats = server.stats()
        dtok = stats["generated_tokens"] - stats["requests_completed"]
        ds = wall
        if rec is not None:
            ph = rec.summary(since=n0)["phases"]
            ds = ph.get("decode", {}).get("total_s", wall)
        return (dtok / ds if ds else 0.0)

    best = {"off": 0.0, "aggregate": 0.0, "full": 0.0}
    ledger = None
    with obs.span("trace_forensics_ab"):
        for _ in range(reps):
            best["off"] = max(best["off"], _run(None))
            best["aggregate"] = max(
                best["aggregate"], _run(Ledger(mode="aggregate"))
            )
            ledger = Ledger(mode="full", exemplar_k=3)
            best["full"] = max(best["full"], _run(ledger))
    tps_off = best["off"]
    snap = ledger.snapshot()
    overhead = (
        round((tps_off - best["aggregate"]) / tps_off * 100.0, 2)
        if tps_off else None
    )
    overhead_full = (
        round((tps_off - best["full"]) / tps_off * 100.0, 2)
        if tps_off else None
    )
    return {
        **snap,
        "ab": {
            "geometry": {
                "num_layers": cfg.num_layers, "d_model": cfg.d_model,
                "slots": 4, "max_len": 64, "prefill_chunk": 8,
                "requests": requests, "max_new": max_new, "reps": reps,
            },
            "decode_tokens_per_sec_ledger_off": round(best["off"], 1),
            "decode_tokens_per_sec_ledger_aggregate": round(
                best["aggregate"], 1
            ),
            "decode_tokens_per_sec_ledger_full": round(best["full"], 1),
            "trace_overhead_pct": overhead,
            "trace_overhead_full_pct": overhead_full,
        },
        "trace_overhead_pct": overhead,
    }


def bench_gpt2_serve(
    slots: int = 8,
    prompt_len: int = 64,
    max_new: int = 48,
    requests: int = 24,
    max_len: int = 128,
    decode_attention: str = "kernel",
    sweep_lengths: tuple = (64, 256, 1024),
):
    """GPT-2 serving throughput/latency on the continuous-batching
    engine: decode tokens/sec over the KV-cache decode path plus
    per-request latency percentiles, on a synthetic request stream
    saturating ``slots`` concurrent cache slots.

    ISSUE 5 grows two comparisons around the headline stream:

    - the same stream re-measured with ``decode_attention="reference"``
      (the dense PR 4 hot loop) — ``reference_decode_tokens_per_sec``,
      detail-only, the kernel-on/off A-B at identical geometry;
    - a decode-throughput-vs-context-length sweep (detail-only,
      ``decode_sweep``): short generations at prompt lengths
      ``sweep_lengths`` on a reduced-depth config (geometry recorded in
      the entry) — with the length-aware kernel the curve should
      flatten relative to O(max_len) dense decode; ``kv_blocks_*``
      record how many cache tiles a tick actually visits.

    ISSUE 7 pins the paged-cache win on top: ``paged_capacity``
    (detail) measures max concurrent requests at a FIXED HBM budget,
    paged pool vs dense cache, with prefix sharing live; the headline
    ``max_concurrent_at_hbm`` + ``prefix_hit_rate`` + ``kv_page_size``
    ride the record line. ``chunked_prefill`` (detail) A/Bs p95 TTFT on
    one mixed-length open-loop trace, whole-prompt vs chunked admits.

    The record line carries the resolved ``decode_attention`` mode
    (what actually executed — "kernel" falls back to "reference" math
    off-TPU, and the line must say so).
    """
    import numpy as np

    import mpit_tpu
    from mpit_tpu import obs
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.ops.decode_attention import num_kv_blocks
    from mpit_tpu.serve import Engine, Request, Server

    world = mpit_tpu.init()
    del world  # serving is single-replica here; TP variant is test-covered

    cfg = GPT2Config.small(max_seq_len=max_len, head_dtype=jnp.bfloat16)
    params = jax.jit(GPT2(cfg).init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine, stats, wall, decode_tokens, decode_s, gen = _serve_stream(
        cfg, params, slots=slots, max_len=max_len, prompt_len=prompt_len,
        max_new=max_new, requests=requests,
        decode_attention=decode_attention,
    )
    out = {
        "decode_tokens_per_sec": (
            round(decode_tokens / decode_s, 1) if decode_s else None
        ),
        "decode_attention": engine.decode_attention_mode,
        # Off-TPU "kernel" mode falls back to reference ATTENTION but
        # keeps the blocked sampler (pure XLA) — this detail key is what
        # distinguishes that engine from a true decode_attention=
        # "reference" run, which is dense end to end.
        "decode_sampler": engine.decode_sampler,
        "serve_tokens_per_sec": round(gen / wall, 1),
        "latency_p50_s": stats.get("latency_p50_s"),
        "latency_p95_s": stats.get("latency_p95_s"),
        "ttft_p50_s": stats.get("ttft_p50_s"),
        "ttft_p95_s": stats.get("ttft_p95_s"),
        "slots": slots,
        "requests": requests,
        "generated_tokens": gen,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new,
        "ticks": stats["ticks"],
        "occupancy_mean": stats["occupancy_mean"],
    }
    # ISSUE 8: the honest decode bandwidth — achieved bytes from the
    # kernel's visited-tile model (accumulated per tick by the
    # scheduler; pinned == the kernel's own visited counts) over the
    # measured decode seconds. A PERCENTAGE of the chip's HBM peak only
    # when the run was ON the chip — off-TPU the line carries null +
    # the platform label (modeled GB/s stays detail-only either way).
    platform = jax.devices()[0].platform
    hbm_bytes = stats.get("decode_hbm_bytes_modeled")
    out["engine_compiles"] = stats.get("engine_compiles")
    out["roofline_platform"] = platform
    out["decode_hbm_util_pct"] = None
    if hbm_bytes and decode_s:
        out["decode_hbm_gbps_modeled"] = round(
            hbm_bytes / decode_s / 1e9, 2
        )
        if platform == "tpu":
            from mpit_tpu.obs.roofline import chip_peaks

            out["decode_hbm_util_pct"] = round(
                100.0 * hbm_bytes / decode_s / chip_peaks()["peak_hbm"], 2
            )
    # Kernel-on/off A-B at identical geometry (detail-only). Guard on the
    # RESOLVED mode: off-TPU a requested "kernel" already ran reference
    # ATTENTION, so a second stream could only A-B the blocked-vs-dense
    # sampler — not the kernel claim this number exists to pin — at
    # double the runtime; skip it and let decode_sampler (above) record
    # which head the measured stream actually ran.
    if engine.decode_attention_mode != "reference":
        _, rstats, rwall, rtok, rdecode_s, rgen = _serve_stream(
            cfg, params, slots=slots, max_len=max_len,
            prompt_len=prompt_len, max_new=max_new, requests=requests,
            decode_attention="reference",
        )
        out["reference_decode_tokens_per_sec"] = (
            round(rtok / rdecode_s, 1) if rdecode_s else None
        )
    # Context-length sweep (detail-only): decode cost vs cached context
    # inside ONE long-cache engine — THE tentpole claim ("scale with
    # context, not cache size") in curve form. Every point shares the
    # same engine/cache geometry (max_len fits the longest context), so
    # the dense reference pays the full buffer at every length while
    # the length-aware kernel pays ceil((L+1)/block_k) tiles. Reduced
    # depth keeps the sweep affordable; the CURVE, not the absolute
    # rate, is the signal — geometry is recorded alongside.
    sweep_cfg = GPT2Config.small(
        num_layers=2,
        max_seq_len=max(sweep_lengths) + 32,
        head_dtype=jnp.bfloat16,
    )
    sweep_params = jax.jit(GPT2(sweep_cfg).init)(
        jax.random.key(1), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    s_len = max(sweep_lengths) + 16
    s_slots, s_new = 4, 8
    sweep_engine = Engine(
        sweep_cfg, sweep_params, slots=s_slots, max_len=s_len,
        prefill_len=max(sweep_lengths),
        decode_attention=decode_attention,
    )
    rng = np.random.RandomState(1)
    bk = sweep_engine.decode_block_k
    rec = obs.get_recorder()

    def _sweep_point(ctx, warm=False):
        sweep_engine.reset()
        server = Server(sweep_engine)
        for i in range(s_slots):
            server.submit(
                Request(
                    rid=i,
                    prompt=rng.randint(
                        0, sweep_cfg.vocab_size, size=ctx
                    ).tolist(),
                    max_new_tokens=2 if warm else s_new,
                )
            )
        n0 = rec.event_count() if rec else 0
        t0 = time.perf_counter()
        server.run()
        wall = time.perf_counter() - t0
        stats = server.stats()
        dtok = stats["generated_tokens"] - stats["requests_completed"]
        ds = wall
        if rec is not None:
            ph = rec.summary(since=n0)["phases"]
            ds = ph.get("decode", {}).get("total_s", wall)
        return dtok, ds

    with obs.span("warmup", calls=1):
        _sweep_point(min(sweep_lengths), warm=True)  # the two compiles
    sweep = []
    for ctx in sweep_lengths:
        dtok, ds = _sweep_point(ctx)
        sweep.append(
            {
                "context_len": ctx,
                "decode_tokens_per_sec": round(dtok / ds, 1) if ds else None,
                "kv_blocks_visited_per_slot": int(
                    num_kv_blocks(np.asarray([ctx]), 1, s_len, bk)[0]
                ),
                "kv_blocks_total": s_len // bk,
            }
        )
    out["decode_sweep"] = {
        "config": {
            "num_layers": sweep_cfg.num_layers,
            "d_model": sweep_cfg.d_model,
            "slots": s_slots,
            "max_new": s_new,
            "max_len": s_len,
            "block_k": bk,
            "decode_attention": sweep_engine.decode_attention_mode,
        },
        "points": sweep,
    }
    # ISSUE 7: the paged-cache capacity win + chunked-prefill TTFT A/B
    # (full blocks detail-only; the line gets the headline triple).
    out["paged_capacity"] = _paged_capacity_block()
    out["chunked_prefill"] = _chunked_prefill_block()
    out["kv_page_size"] = out["paged_capacity"]["page_size"]
    out["prefix_hit_rate"] = out["paged_capacity"]["paged"][
        "prefix_hit_rate"
    ]
    out["max_concurrent_at_hbm"] = out["paged_capacity"]["paged"][
        "max_concurrent"
    ]
    # ISSUE 13: the speculative-decode A/B (same seeded traces, spec
    # on/off, self-speculation draft). The block stays detail-only; the
    # achieved tokens-per-slot-tick multiplier rides the record line.
    out["speculative"] = _speculative_block()
    out["accepted_tokens_per_tick"] = out["speculative"][
        "accepted_tokens_per_tick"
    ]
    # ISSUE 15: the quantized-KV A/B + capacity sweep + quality gates
    # (trained checkpoint). Block detail-only; the line carries the
    # headline stream's wire dtype and the capacity-at-fixed-HBM ratio.
    out["quantized_kv"] = _quantized_kv_block()
    out["kv_dtype"] = engine.kv_dtype
    out["q8_capacity_ratio"] = out["quantized_kv"]["q8_capacity_ratio"]
    # ISSUE 17: the quantized-WEIGHTS A/B + capacity + quality gates
    # (trained checkpoint; the param read is the dominant tick term the
    # KV block's honesty note pointed at). Block detail-only; the line
    # carries the headline stream's weight wire dtype and the modeled
    # int8-vs-f32 whole-tick decode-bytes ratio.
    out["quantized_weights"] = _quantized_weights_block()
    out["weights_dtype"] = engine.weights_dtype
    out["q8w_bytes_ratio"] = out["quantized_weights"]["q8w_bytes_ratio"]
    # ISSUE 16: the request-ledger overhead A/B + forensics snapshot
    # (block detail-only; the line carries the aggregate-arm overhead
    # pct and the exemplar count proving tail capture ran).
    out["trace_forensics"] = _trace_forensics_block()
    out["trace_overhead_pct"] = out["trace_forensics"]["trace_overhead_pct"]
    out["exemplars_retained"] = out["trace_forensics"]["exemplars_retained"]
    # ISSUE 18: the headline stream's byte-exact memory-ledger stats —
    # the dense engine's measured held-bytes peak and the KV headroom
    # floor across the whole stream. The full block (per-subsystem
    # decomposition, per-request/per-tenant attribution, conservation
    # verdict, platform-labeled reconciliation) is detail-only; the
    # peak + headroom floor ride the record line.
    out["memory"] = stats.get("memory", {})
    out["hbm_held_peak_bytes"] = out["memory"].get("held_peak_bytes")
    out["kv_headroom_min_pct"] = out["memory"].get("kv_headroom_min_pct")
    return out


def bench_gpt2_slo(
    slots: int = 4,
    max_len: int = 64,
    prefill_len: int = 16,
    duration_s: float = 2.5,
    rate_fractions: tuple = (0.4, 0.7, 1.0, 1.5),
    ttft_multiple: float = 5.0,
    window_s: float = 1.5,
):
    """The SLO sweep (ISSUE 6; ROADMAP item 4's headline metric): **max
    sustained requests/s at p95 TTFT ≤ target**, measured by driving
    the continuous-batching engine with OPEN-loop Poisson arrivals
    (``serve.loadgen`` + ``Server.run_timed``) at a ladder of rates and
    reading windowed percentiles off the streaming sketch
    (``obs.stream``) — never the Recorder's bounded buffer.

    Self-calibrating so the sweep means the same thing on CPU and TPU:

    - **capacity** — a closed-loop saturation run measures the rate the
      engine drains when arrival timing is no constraint; sweep rates
      are ``rate_fractions`` of it, so the ladder straddles saturation
      by construction and the top point OVERLOADS (its queue grows
      without bound, TTFT explodes, the ``ttft_p95`` SLO trips —
      ``slo_breach`` instants land in this workload's recorder and ride
      its ``obs_baseline`` snapshot into BENCH_DETAIL.json);
    - **ttft target** — ``ttft_multiple`` × the measured unloaded TTFT
      (sequential single-request median): "p95 within 5× of an idle
      server", an SLO that scales with the hardware instead of going
      vacuous on a slow host.

    A rate point is SUSTAINED when its whole-run sketch p95 TTFT meets
    the target and the SLO monitor spent ≤ 20% of the window in breach.
    The record line carries the headline + target + total breaches; the
    rate → (p95 TTFT, tokens/s, breach fraction) curve is detail-only.
    """
    import numpy as np

    import mpit_tpu
    from mpit_tpu import obs
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.obs.slo import SLO, SLOMonitor
    from mpit_tpu.obs.stream import StreamRegistry
    from mpit_tpu.serve import (
        Engine,
        LoadSpec,
        Request,
        RequestClass,
        Server,
        generate_arrivals,
        warm_engine,
    )

    world = mpit_tpu.init()
    del world

    cfg = GPT2Config.tiny(max_seq_len=max_len)
    params = jax.jit(GPT2(cfg).init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine = Engine(
        cfg, params, slots=slots, max_len=max_len, prefill_len=prefill_len
    )
    mix = (
        RequestClass("interactive", weight=0.8, prompt_len=(2, 10),
                     max_new_tokens=(3, 8)),
        RequestClass("batch", weight=0.2, prompt_len=(8, prefill_len - 2),
                     max_new_tokens=(8, 20)),
    )
    mean_new = sum(
        c.weight * (c.max_new_tokens[0] + c.max_new_tokens[1]) / 2
        for c in mix
    ) / sum(c.weight for c in mix)
    rng = np.random.RandomState(0)

    def _mk_req(i, klass):
        # Inclusive [lo, hi], same convention as loadgen's sampler —
        # the calibration requests and the sweep traffic must draw
        # from the same distribution.
        plen = int(rng.randint(klass.prompt_len[0], klass.prompt_len[1] + 1))
        return Request(
            rid=f"cal{i}",
            prompt=rng.randint(0, cfg.vocab_size, size=plen).tolist(),
            max_new_tokens=int(
                rng.randint(klass.max_new_tokens[0],
                            klass.max_new_tokens[1] + 1)
            ),
        )

    warm_engine(engine)  # spans itself as `warmup` (ISSUE 8 satellite)

    # Calibration 1 — unloaded TTFT: sequential single requests on an
    # idle engine; the SLO target's basis.
    with obs.span("calibrate_ttft"):
        ttfts = []
        for i in range(5):
            engine.reset()
            s = Server(engine)
            s.submit(_mk_req(i, mix[0]))
            s.run()
            ttfts.append(s.completed[0].ttft_s)
        unloaded_ttft = float(np.median(ttfts))
    ttft_target = ttft_multiple * unloaded_ttft

    # Calibration 2 — closed-loop capacity: saturate the slots, measure
    # the drain rate. Arrival timing can only LOWER throughput, so this
    # is the ceiling the sweep fractions scale from.
    with obs.span("calibrate_capacity"):
        engine.reset()
        s = Server(engine)
        n_cal = slots * 8
        for i in range(n_cal):
            s.submit(_mk_req(i, mix[int(rng.rand() < 0.2)]))
        t0 = time.perf_counter()
        s.run()
        cal_wall = time.perf_counter() - t0
        capacity = n_cal / cal_wall

    sweep = []
    breaches_total = 0
    max_sustained = None
    for frac in rate_fractions:
        rate = frac * capacity
        engine.reset()
        registry = StreamRegistry(window_s=window_s)
        monitor = SLOMonitor(
            [SLO.ttft_p95(ttft_target)], registry, min_count=8
        )
        arrivals = generate_arrivals(
            LoadSpec(rate=rate, classes=mix),
            vocab_size=cfg.vocab_size,
            duration_s=duration_s,
            seed=int(frac * 100),
        )
        server = Server(engine, stream=registry, slo=monitor)
        with obs.span("slo_point", rate=round(rate, 1)):
            t0 = time.perf_counter()
            # drain=False: past saturation the queue never drains — the
            # honest measurement is what completed inside the window.
            server.run_timed(arrivals, duration=duration_s, drain=False)
            wall = time.perf_counter() - t0
        stats = server.stats()
        sk = registry.total_sketch("request_ttft")
        p95 = sk.quantile(0.95) if sk is not None and sk.count else None
        rep = monitor.report()["targets"]["ttft_p95"]
        breach_frac = rep["time_in_breach_s"] / max(wall, 1e-9)
        gen = stats["generated_tokens"]
        sustained = (
            p95 is not None
            and p95 <= ttft_target
            and breach_frac <= 0.2
        )
        offered = len(arrivals) / duration_s
        if sustained:
            max_sustained = max(max_sustained or 0.0, offered)
        breaches_total += rep["breaches"]
        sweep.append(
            {
                "rate_fraction": frac,
                "offered_req_per_s": round(offered, 2),
                "completed_req_per_s": round(
                    stats["requests_completed"] / wall, 2
                ),
                "ttft_p95_s": round(p95, 6) if p95 is not None else None,
                "tokens_per_sec": round(gen / wall, 1),
                "breach_fraction": round(breach_frac, 4),
                "breaches": rep["breaches"],
                "truncated": stats["truncated"],
                "sustained": sustained,
            }
        )
    return {
        "max_sustained_req_per_s": (
            round(max_sustained, 2) if max_sustained is not None else None
        ),
        "ttft_target_s": round(ttft_target, 6),
        "slo_breaches": breaches_total,
        "decode_attention": engine.decode_attention_mode,
        "slots": slots,
        "calibration": {
            "unloaded_ttft_s": round(unloaded_ttft, 6),
            "ttft_multiple": ttft_multiple,
            "closed_loop_capacity_req_per_s": round(capacity, 2),
            "mean_new_tokens": round(mean_new, 2),
        },
        "rate_sweep": sweep,
        "geometry": {
            "num_layers": cfg.num_layers,
            "d_model": cfg.d_model,
            "slots": slots,
            "max_len": max_len,
            "prefill_len": prefill_len,
            "duration_s": duration_s,
            "window_s": window_s,
            "process": "poisson",
        },
    }


def bench_gpt2_policy(
    slots: int = 4,
    max_len: int = 64,
    prefill_len: int = 32,
    kv_pages: int = 20,
    kv_page_size: int = 8,
    prefill_chunk: int = 8,
    duration_s: float = 2.0,
    rate_fractions: tuple = (0.4, 0.7, 1.0, 1.6),
    ttft_multiple: float = 15.0,
    window_s: float = 1.5,
):
    """The scheduling-policy A/B (ISSUE 12; ROADMAP item 4's decision
    layer): the SAME paged engine at the SAME HBM budget driven by the
    SAME seeded mixed 80/20 open-loop traces, FIFO vs the policy tier
    (priority classes + deficit-round-robin tenant fairness +
    projected-TTFT admission + paged-KV preemption), swept over a
    self-calibrating rate ladder like ``gpt2_slo``:

    - **ttft target** — ``ttft_multiple`` × the measured unloaded
      interactive TTFT, stamped on the interactive class (priority 0);
      the batch class (priority 1) carries no target — it is the
      preemption victim pool;
    - **sustained** — a rate point sustains when the INTERACTIVE class's
      exact p95 TTFT (completions, not sketch) meets the target, the
      tier-0 SLO monitor spent ≤ 20% of the window in breach, and ≤ 10%
      of arrivals were shed (a policy that sheds its way to a good p95
      has not sustained the rate);
    - the pool is undersized (``kv_pages < slots × pages_per_slot``) so
      page pressure is real and preemption has work to do.

    Record line: ``max_sustained_req_per_s_policy`` (the headline — the
    FIFO counterpart sits in detail for the ≥ comparison),
    ``interactive_ttft_p95_ms`` (policy, at the top swept rate; FIFO's
    in detail) and ``preemptions``. A per-rate FIFO-vs-policy curve,
    shed-cause splits and the sentinel/SLO wiring evidence are
    detail-only. CPU runs are honest wall-clock measurements of this
    host — platform-labeled via the record's top-level ``platform``, no
    fabricated utilization (roofline honesty rule).
    """
    import dataclasses as _dc

    import numpy as np

    import mpit_tpu
    from mpit_tpu import obs
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.obs.slo import SLO, SLOMonitor
    from mpit_tpu.obs.stream import StreamRegistry
    from mpit_tpu.serve import (
        Engine,
        LoadSpec,
        Request,
        RequestClass,
        SchedulingPolicy,
        Server,
        generate_arrivals,
        warm_engine,
    )
    from mpit_tpu.serve.policy import PolicyConfig

    world = mpit_tpu.init()
    del world

    cfg = GPT2Config.tiny(max_seq_len=max_len)
    params = jax.jit(GPT2(cfg).init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine = Engine(
        cfg, params, slots=slots, max_len=max_len, prefill_len=prefill_len,
        kv_pages=kv_pages, kv_page_size=kv_page_size,
        prefill_chunk=prefill_chunk,
    )
    interactive = RequestClass(
        "interactive", weight=0.8, prompt_len=(2, 10),
        max_new_tokens=(3, 8), priority=0,
    )
    batch = RequestClass(
        "batch", weight=0.2, prompt_len=(12, prefill_len - 2),
        max_new_tokens=(12, 24), priority=1,
    )
    rng = np.random.RandomState(0)

    def _mk_req(i, klass):
        plen = int(rng.randint(klass.prompt_len[0], klass.prompt_len[1] + 1))
        return Request(
            rid=f"cal{i}",
            prompt=rng.randint(0, cfg.vocab_size, size=plen).tolist(),
            max_new_tokens=int(
                rng.randint(klass.max_new_tokens[0],
                            klass.max_new_tokens[1] + 1)
            ),
        )

    warm_engine(engine)

    # Calibration 1 — unloaded interactive TTFT: the target's basis.
    with obs.span("calibrate_ttft"):
        ttfts = []
        for i in range(5):
            engine.reset()
            s = Server(engine)
            s.submit(_mk_req(i, interactive))
            s.run()
            ttfts.append(s.completed[0].ttft_s)
        unloaded_ttft = float(np.median(ttfts))
    ttft_target = ttft_multiple * unloaded_ttft
    interactive = _dc.replace(interactive, ttft_target_s=ttft_target)
    mix = (interactive, batch)

    # Calibration 2 — closed-loop capacity (the ladder's 1.0 point).
    with obs.span("calibrate_capacity"):
        engine.reset()
        s = Server(engine)
        n_cal = slots * 8
        for i in range(n_cal):
            s.submit(_mk_req(i, mix[int(rng.rand() < 0.2)]))
        t0 = time.perf_counter()
        s.run()
        capacity = n_cal / (time.perf_counter() - t0)

    def _run_point(arrivals, by_rid, use_policy, ledger=None, eng=None,
                   drain=False):
        eng = engine if eng is None else eng
        eng.reset()
        registry = StreamRegistry(window_s=window_s)
        sentinel = obs.Sentinel(phases=("decode", "prefill"), warmup=4)
        # The SLO watches the INTERACTIVE tier's TTFT series (fed for
        # priority/target-stamped traffic on FIFO runs too, so the A/B
        # reads one metric); breaches land in the sentinel per the
        # ISSUE 12 acceptance wiring.
        monitor = SLOMonitor(
            [SLO(name="interactive_ttft_p95",
                 metric="request_ttft_tier0", max_value=ttft_target)],
            registry, min_count=8, sentinel=sentinel,
        )
        policy = (
            SchedulingPolicy(PolicyConfig(min_samples=4), registry)
            if use_policy
            else None
        )
        server = Server(
            eng, sentinel=sentinel, stream=registry, slo=monitor,
            policy=policy, ledger=ledger,
        )
        t0 = time.perf_counter()
        server.run_timed(arrivals, duration=duration_s, drain=drain)
        wall = time.perf_counter() - t0
        stats = server.stats()
        done = server.completed

        def _class_p95(name):
            vals = [
                c.ttft_s for c in done if by_rid[c.rid].klass == name
            ]
            return (
                float(np.percentile(np.asarray(vals), 95))
                if vals else None
            )

        p95_int = _class_p95("interactive")
        p95_bat = _class_p95("batch")
        rep = monitor.report()["targets"]["interactive_ttft_p95"]
        breach_frac = rep["time_in_breach_s"] / max(wall, 1e-9)
        shed_frac = len(server.shed) / max(len(arrivals), 1)
        sustained = (
            p95_int is not None
            and p95_int <= ttft_target
            and breach_frac <= 0.2
            and shed_frac <= 0.1
        )
        entry = {
            "completed_req_per_s": round(
                stats["requests_completed"] / wall, 2
            ),
            "interactive_ttft_p95_s": (
                round(p95_int, 6) if p95_int is not None else None
            ),
            "batch_ttft_p95_s": (
                round(p95_bat, 6) if p95_bat is not None else None
            ),
            "tokens_per_sec": round(stats["generated_tokens"] / wall, 1),
            "breaches": rep["breaches"],
            "breach_fraction": round(breach_frac, 4),
            "shed_fraction": round(shed_frac, 4),
            "truncated": stats["truncated"],
            "sustained": sustained,
            "sentinel_clean": sentinel.report()["clean"],
        }
        if use_policy:
            entry["preemptions"] = stats["preemptions"]
            entry["shed_admission"] = stats.get(
                "requests_shed_admission", 0
            )
            entry["shed_queue_full"] = stats.get(
                "requests_shed_queue_full", 0
            )
        # ISSUE 20 tiering A/B evidence: the resume-path p95s (present
        # once the mode's resumes have fired — restream on the tiered
        # engine, recompute on the untiered one), the prefix hit rate
        # the host tier is supposed to hold up, and — tiered runs only —
        # the host-tier counters/byte totals.
        for k in ("resume_restream_p95_s", "resume_recompute_p95_s",
                  "prefix_hit_rate"):
            if k in stats:
                entry[k] = stats[k]
        if "host_restreamed_pages" in stats:
            entry["host"] = {
                k: stats[k]
                for k in ("kv_host_pages", "host_spilled_pages",
                          "host_restreamed_pages", "host_prefix_hits",
                          "parked_spills", "spilled_prefix_entries")
            }
            entry["host"]["spill_bytes_total"] = (
                stats["memory"]["spill_bytes_total"]
            )
            entry["host"]["restream_bytes"] = (
                stats["memory"]["restream_bytes"]
            )
            entry["host"]["host_held_peak_bytes"] = (
                stats["memory"]["host_held_peak_bytes"]
            )
        return entry

    sweep = []
    forensics_ledger = None
    max_sustained = {"fifo": None, "policy": None}
    breaches = {"fifo": 0, "policy": 0}
    preemptions_total = 0
    top_p95 = {"fifo": None, "policy": None}
    for frac in rate_fractions:
        rate = frac * capacity
        arrivals = generate_arrivals(
            LoadSpec(rate=rate, classes=mix, tenants=2),
            vocab_size=cfg.vocab_size,
            duration_s=duration_s,
            seed=int(frac * 100),
        )
        by_rid = {a.request.rid: a for a in arrivals}
        offered = len(arrivals) / duration_s
        point = {
            "rate_fraction": frac,
            "offered_req_per_s": round(offered, 2),
        }
        for mode in ("fifo", "policy"):
            # ISSUE 16: the TOP swept rate's policy run carries a full
            # request ledger — past saturation, where sheds / preemption
            # / breach pins all fire, is exactly where why-slow earns
            # its keep. One arm only: the A/B stays ledger-free so the
            # FIFO-vs-policy comparison is untouched.
            ledger = None
            if mode == "policy" and frac == rate_fractions[-1]:
                from mpit_tpu.obs.trace import Ledger

                ledger = forensics_ledger = Ledger(
                    mode="full", exemplar_k=3
                )
            with obs.span("policy_point", rate=round(rate, 1), mode=mode):
                entry = _run_point(
                    arrivals, by_rid, mode == "policy", ledger=ledger
                )
            point[mode] = entry
            breaches[mode] += entry["breaches"]
            if entry["sustained"]:
                max_sustained[mode] = max(
                    max_sustained[mode] or 0.0, offered
                )
            top_p95[mode] = entry["interactive_ttft_p95_s"]
            if mode == "policy":
                preemptions_total += entry["preemptions"]
        sweep.append(point)

    # ISSUE 20 — the HBM→host tiering A/B: the SAME policy engine
    # geometry at the SAME saturated rate (the ladder's top fraction),
    # but on a LONG-TAIL trace — every request opens with a shared
    # 16-token system prefix, so the undersized pool reclaims the
    # prefix pages over and over. Untiered, the reclaim kills the
    # entry and every later admit recomputes (and every preemption
    # resume recomputes its fill); tiered, the entry and parked
    # victims spill to host RAM and restream. Both arms DRAIN so every
    # parked victim actually resumes and the p95s compare the same
    # completed population. CPU honesty: this host's "host tier" is a
    # same-RAM copy through the jitted gather/scatter, so the measured
    # restream p95 is an honest wall-clock for THIS platform but NOT a
    # PCIe/DMA measurement — the modeled per-page figure next to it is
    # the labeled transfer estimate.
    tail_mix = (
        _dc.replace(interactive, prefix_len=16),
        _dc.replace(batch, prompt_len=(4, 14), prefix_len=16),
    )
    # Bursty, not Poisson: the steady saturated stream always has a
    # CONCURRENT reader on the shared prefix, so its entry never goes
    # sole-reader and both arms hit alike. Bursts at 4× the mean rate
    # bring the preemption pressure (parks → restream resumes);
    # the silent off-phases drain the pool, the prefix goes
    # sole-reader, and the reclaim that untiered kills — and the host
    # tier survives — actually happens, burst after burst.
    top_rate = rate_fractions[-1] * capacity
    tail_arrivals = generate_arrivals(
        LoadSpec(rate=top_rate, classes=tail_mix, tenants=2,
                 process="bursty", on_fraction=0.25, mean_on_s=0.25),
        vocab_size=cfg.vocab_size,
        duration_s=duration_s,
        seed=777,
    )
    tail_by_rid = {a.request.rid: a for a in tail_arrivals}
    tiered_engine = Engine(
        cfg, params, slots=slots, max_len=max_len, prefill_len=prefill_len,
        kv_pages=kv_pages, kv_page_size=kv_page_size,
        prefill_chunk=prefill_chunk, kv_host_pages=kv_pages,
    )
    warm_engine(tiered_engine)
    tier_ab = {}
    for tmode, eng_used in (("untiered", engine), ("tiered", tiered_engine)):
        with obs.span("tiering_point", mode=tmode):
            tier_ab[tmode] = _run_point(
                tail_arrivals, tail_by_rid, True, eng=eng_used, drain=True
            )

    def _ms(v):
        return round(v * 1e3, 2) if v is not None else None

    # ISSUE 16: the saturated policy run's ledger snapshot, worst three
    # exemplars only (pinned-or-slowest; dropping exemplars is lossless
    # for why-slow's usability contract — dropping EVENTS is not, and
    # never happens: the event cap is far above a bench request's life).
    forensics = None
    if forensics_ledger is not None:
        forensics = forensics_ledger.snapshot()
        # exemplars_retained stays the TRUE retention count (breach
        # pins under saturation retain the whole in-flight set);
        # exemplars_stored says how many ride the artifact.
        forensics["exemplars"] = forensics["exemplars"][:3]
        forensics["exemplars_stored"] = len(forensics["exemplars"])

    # The line's tiering triple (ISSUE 20): p95 resume-via-restream
    # (tiered arm) vs p95 resume-via-recompute (untiered arm) on the
    # same drained long-tail trace, and the prefix hit rate the host
    # tier held up under pool pressure ("hit_rate" — the untiered
    # counterpart it must beat sits in tiering_detail). A p95 is null
    # until its arm's resumes fired — never fabricated.
    t_ent = tier_ab["tiered"]
    u_ent = tier_ab["untiered"]
    page_bytes = tiered_engine.page_bytes
    host_link_gbps = 16.0  # assumed PCIe gen4-ish effective host link
    tiering_detail = {
        "prefix_hit_rate_tiered": t_ent.get("prefix_hit_rate"),
        "prefix_hit_rate_untiered": u_ent.get("prefix_hit_rate"),
        "kv_host_pages": kv_pages,
        "shared_prefix_len": 16,
        "offered_req_per_s": round(len(tail_arrivals) / duration_s, 2),
        "untiered": u_ent,
        "tiered": t_ent,
        # The labeled transfer model (never passed off as measured):
        # one page over an assumed host link, plus the same-RAM
        # platform note that keeps the measured p95 honest.
        "host_link_gbps_assumed": host_link_gbps,
        "modeled_page_restream_us": round(
            (page_bytes / (host_link_gbps * 1e9) + 10e-6) * 1e6, 2
        ),
        "note": "CPU host tier is a same-RAM copy; measured restream "
                "p95 is wall-clock on this host, not a PCIe/DMA "
                "measurement",
    }

    return {
        "trace_forensics": forensics,
        "tiering": {
            "restream_p95_ms": _ms(t_ent.get("resume_restream_p95_s")),
            "recompute_p95_ms": _ms(u_ent.get("resume_recompute_p95_s")),
            "hit_rate": t_ent.get("prefix_hit_rate"),
        },
        "tiering_detail": tiering_detail,
        "max_sustained_req_per_s_policy": (
            round(max_sustained["policy"], 2)
            if max_sustained["policy"] is not None else None
        ),
        "max_sustained_req_per_s_fifo": (
            round(max_sustained["fifo"], 2)
            if max_sustained["fifo"] is not None else None
        ),
        # The top swept rate's interactive p95 — the mixed 80/20 trace
        # past saturation, where the tiers earn their keep.
        "interactive_ttft_p95_ms": _ms(top_p95["policy"]),
        "interactive_ttft_p95_ms_fifo": _ms(top_p95["fifo"]),
        "preemptions": preemptions_total,
        "ttft_target_s": round(ttft_target, 6),
        "slo_breaches": breaches,
        "decode_attention": engine.decode_attention_mode,
        "calibration": {
            "unloaded_ttft_s": round(unloaded_ttft, 6),
            "ttft_multiple": ttft_multiple,
            "closed_loop_capacity_req_per_s": round(capacity, 2),
        },
        "rate_sweep": sweep,
        "geometry": {
            "num_layers": cfg.num_layers,
            "d_model": cfg.d_model,
            "slots": slots,
            "max_len": max_len,
            "prefill_len": prefill_len,
            "kv_pages": kv_pages,
            "kv_page_size": kv_page_size,
            "prefill_chunk": prefill_chunk,
            "duration_s": duration_s,
            "window_s": window_s,
            "tenants": 2,
            "mix": "interactive 0.8 p0 / batch 0.2 p1",
        },
    }


def _q8_wire_bytes(payload_bytes: int, p: int) -> float:
    """ACTUAL wire-equivalent payload of a quantized (int8 + per-chunk
    scale) ring over an f32 payload — the ring planner's own figure
    (ISSUE 9: modeled q8 numbers use the quantized size, never the
    logical one)."""
    from mpit_tpu.ops.ring_collectives import plan_ring

    plan = plan_ring(payload_bytes // 4, p, "int8")
    return plan.wire_payload_bytes("int8", scales=True)


def _modeled_allreduce_curves(mbs, p: int = 8):
    """Modeled GB/s per payload for the three sync variants (psum and
    ring share the ring-allreduce model — XLA's psum IS a ring; q8 runs
    the same model at its int8 wire size, reported as ALGORITHM GB/s —
    logical payload over wall time, the EQuARX framing where the
    quantized collective looks ~4× faster because it moves ~¼ the
    bytes). Modeled, labeled, never passed off as measured."""
    from mpit_tpu.utils import (
        modeled_all_gather_seconds,
        modeled_allreduce_seconds,
        modeled_reduce_scatter_seconds,
    )

    out = {}
    for mb in mbs:
        payload = mb * 2**20
        t_ring = modeled_allreduce_seconds(payload, p)
        wire_q8 = _q8_wire_bytes(payload, p)
        t_q8 = modeled_reduce_scatter_seconds(
            wire_q8, p
        ) + modeled_all_gather_seconds(wire_q8, p)
        out[str(mb)] = {
            "psum": round(payload / t_ring / 1e9, 2),
            "ring": round(payload / t_ring / 1e9, 2),
            "q8": round(payload / t_q8 / 1e9, 2),
        }
    return out


def bench_allreduce(payload_mb: int = 64, iters: int = 10):
    """The BASELINE "allreduce GB/s" metric — now a three-way record
    (ISSUE 9): stock ``lax.psum`` vs the in-kernel Pallas ring vs the
    quantized (int8 + per-chunk scales) ring.

    Measured only on TPU with >1 device; elsewhere (1 chip, or a CPU
    mesh whose "wire" is memcpy) the latency-aware ICI ring model for 8
    chips is reported and labeled — never passed off as measured
    (SURVEY.md §8.4.5). GB/s is ALGORITHM bandwidth (logical payload /
    time, the MPI convention) for every variant — the q8 figure exceeds
    the wire ceiling by design since its wire bytes are ~¼ the payload.
    """
    import mpit_tpu
    from jax.sharding import PartitionSpec as P
    from mpit_tpu.comm import collectives as C
    from mpit_tpu.utils import TPU_V5E, allreduce_gbps

    world = mpit_tpu.init()
    n = world.num_devices
    platform = jax.devices()[0].platform
    payload = payload_mb * 1024 * 1024
    if n == 1 or platform != "tpu":
        from mpit_tpu.utils import modeled_allreduce_seconds

        # Latency-aware ring model (utils/profiling.py): the derived
        # GB/s MOVES with payload (small payloads latency-bound, large
        # ones approach the 2×ICI wire ceiling). Off-TPU the ring
        # kernels fall back to lax anyway (mode-stamped), so a
        # multi-device CPU "measurement" would time memcpy — the model
        # is the only honest figure here. Still modeled, still labeled.
        modeled = payload / modeled_allreduce_seconds(payload, 8) / 1e9
        curves = _modeled_allreduce_curves((1, 4, 16, 64, 256))
        at = curves[str(payload_mb)] if str(payload_mb) in curves else (
            _modeled_allreduce_curves((payload_mb,))[str(payload_mb)]
        )
        return {
            "gbps": round(modeled, 2),
            # ring == psum by model (both are bandwidth-optimal rings);
            # the MEASURED separation is what a TPU run records.
            "ring_gbps": at["ring"],
            "q8_gbps": at["q8"],
            "modeled": True,
            "platform": platform,
            "payload_mb": payload_mb,
            "by_payload_mb": curves,
            "q8_wire_bytes_at_payload": round(_q8_wire_bytes(payload, 8)),
            "ici_hop_latency_us_assumed": TPU_V5E.ici_hop_latency * 1e6,
            "note": f"{n} device(s) on {platform}: latency-aware ICI "
                    "ring estimate for 8 chips; no GB/s measured off-TPU",
        }
    # Ring variants measure the BUCKETED production path (GradSync,
    # 4 MB buckets — the configuration grad_sync="ring|ring_q8"
    # actually runs): the ring kernels are VMEM-resident, so a
    # monolithic 64 MB payload would not even compile; the bucket loop
    # is the real wire schedule. allreduce_grads is mean-semantics
    # (sum + a scalar multiply) — bandwidth-equivalent to psum.
    from mpit_tpu.train import GradSync

    ring_sync = GradSync("data", "ring")
    q8_sync = GradSync("data", "ring_q8")
    variants = (
        ("psum", lambda v: C.allreduce(v, "data")),
        ("ring", lambda v: ring_sync.allreduce_grads(v)),
        ("q8", lambda v: q8_sync.allreduce_grads(v)),
    )

    def timed(body, xs, reps):
        # MPI convention (and the modeled branch above): each device
        # reduces a payload-sized PER-RANK buffer — n × payload bytes
        # globally, one shard per device.
        f = jax.jit(
            world.shard_map(body, in_specs=P("data"), out_specs=P("data"))
        )
        out = f(xs)
        float(out[0, 0])  # warm + force
        t0 = time.perf_counter()
        for _ in range(reps):
            out = f(out)
        float(out[0, 0])
        return (time.perf_counter() - t0) / reps

    # One pass over the ladder; the headline payload is measured at the
    # full ``iters`` count and its row doubles as the headline figures
    # (no second compile+measurement of the same geometry).
    ladder = {}
    for mb in (1, 4, 16, 64, 256):
        pl_b = mb * 2**20
        xs = jnp.ones((n, pl_b // 4), jnp.float32)
        reps = iters if pl_b == payload else max(3, iters // 2)
        ladder[str(mb)] = {
            name: round(allreduce_gbps(pl_b, n, timed(body, xs, reps)), 2)
            for name, body in variants
        }
    headline = ladder.get(str(payload_mb))
    if headline is None:  # off-ladder payload: measure it directly
        xs = jnp.ones((n, payload // 4), jnp.float32)
        headline = {
            name: round(allreduce_gbps(payload, n, timed(body, xs, iters)), 2)
            for name, body in variants
        }
    return {
        "gbps": headline["psum"],
        "ring_gbps": headline["ring"],
        "q8_gbps": headline["q8"],
        "modeled": False,
        "platform": platform,
        "devices": n,
        "payload_mb": payload_mb,
        "by_payload_mb": ladder,
        "q8_wire_bytes_at_payload": round(_q8_wire_bytes(payload, n)),
    }


def _round1_baselines():
    """Round-1 recorded values — the cross-round baseline per the judge's
    protocol ("the measured single-chip numbers are the cross-round
    baseline now", VERDICT.md round 1). Read from BENCH_r01.json so a
    corrected record propagates; constants are the fallback."""
    alex, gpt2 = 18007.75, 66687.0
    path = os.path.join(_REPO, "BENCH_r01.json")
    try:
        with open(path) as f:
            rec = json.load(f)["parsed"]
        alex = rec["value"]
        gpt2 = rec["detail"]["gpt2"]["tokens_per_sec"]
    except (OSError, KeyError, ValueError):
        pass
    return alex, gpt2


def bench_mnist_easgd(steps: int = 120, replicas: int = 2):
    """The elastic EASGD tier's robustness record (ISSUE 11).

    Four seeded runs on the synthetic-MNIST accuracy loop:

    1. sync-SPMD baseline (the accuracy oracle);
    2. no-fault elastic fleet (1 anchor + ``replicas`` replicas on
       ``hardened_loop``) — ``easgd_acc_delta_vs_sync`` is the pinned
       "matches sync within noise" contract (EQuARX-style accuracy pin);
    3. the same fleet with an injected straggler (``FaultPlan.slowdown``
       on the last replica): ``straggler_healthy_throughput_pct`` =
       healthy replicas' best-window throughput vs the no-fault run —
       the "a straggler delays only its own anchor pulls" claim,
       measured; the flight recorder's skew report names the straggler;
    4. kill-at-step + crash-consistent checkpoint rejoin:
       ``rejoin_steps_to_recover`` = steps re-trained after restoring
       the latest atomic checkpoint.

    All faults come from seeded ``FaultPlan``s — rerunning this workload
    reproduces the same event sequences.
    """
    from mpit_tpu import obs
    from mpit_tpu.asyncsgd import mnist
    from mpit_tpu.compat import FaultPlan, Slowdown

    import tempfile

    batch_size = 32
    base_args = [
        "--steps", str(steps), "--batch-size", str(batch_size),
        "--log-every", "10", "--seed", "0",
    ]
    elastic_args = base_args + [
        "--mode", "elastic", "--nranks", str(replicas + 1),
        "--sync-every", "4", "--easgd-beta", "0.5",
        "--heartbeat-s", "0.05", "--lease-s", "0.4",
    ]
    straggler_rank = replicas  # last replica (ranks are 1..replicas)

    with obs.span("staging", what="sync_baseline"):
        sync = mnist.main(list(base_args))
    sync_acc = sync["eval"]["top1"]

    def _tput(run, ranks):
        # MEAN logged-window items/sec per replica (compile excluded by
        # window construction; the mean, not the best, because replica
        # threads share host cores and per-window rates are scheduling-
        # noisy), averaged over the requested replica indices. No
        # silent fallback: a replica without the figure (fewer than two
        # log windows) would force a different unit basis — fail loudly
        # instead; the workload then records an "error" entry.
        vals = []
        for i in ranks:
            v = run["replica_stats"][i].get("items_per_sec_mean")
            if v is None:
                raise RuntimeError(
                    f"replica {i} recorded no items_per_sec_mean — "
                    "steps_per_replica/log_every leave <2 logged windows"
                )
            vals.append(v)
        return sum(vals) / len(vals)

    with obs.span("timed_window", what="elastic_nofault"):
        nofault = mnist.main(list(elastic_args))
    acc = nofault["eval"]["accuracy"]

    with obs.span("timed_window", what="elastic_straggler"):
        straggler = mnist.main(
            list(elastic_args),
            fault_plan=FaultPlan(
                seed=0, slowdown={straggler_rank: Slowdown(0.03)}
            ),
        )
    healthy = list(range(replicas - 1))  # replica indices, straggler last
    healthy_pct = 100.0 * _tput(straggler, healthy) / _tput(nofault, healthy)
    skew = straggler["flight"]["skew"].get("step", {})

    # Kill OFF the checkpoint cadence (ckpt_every=10): a kill landing
    # exactly on a just-saved step would make rejoin_steps_to_recover a
    # vacuous 0 — the metric is the re-trained gap, so put the kill
    # mid-interval.
    kill_step = max(steps // replicas // 2, 10) + 5
    with obs.span("timed_window", what="elastic_kill_rejoin"):
        with tempfile.TemporaryDirectory() as td:
            kill = mnist.main(
                list(elastic_args)
                + ["--ckpt-dir", td, "--ckpt-every", "10"],
                fault_plan=FaultPlan(
                    seed=0, kill_at={1: kill_step}, rejoin_delay_s=0.6
                ),
            )
    killed = kill["replica_stats"][0]

    return {
        "easgd_acc_delta_vs_sync": round(acc - sync_acc, 4),
        "straggler_healthy_throughput_pct": round(healthy_pct, 1),
        "rejoin_steps_to_recover": killed.get("rejoin_steps_to_recover"),
        # Fleet/fault geometry + per-scenario evidence: detail-only.
        "replicas": replicas,
        "steps_per_replica": nofault["steps_per_replica"],
        "sync_accuracy": round(sync_acc, 4),
        "elastic_accuracy": round(acc, 4),
        "anchor_version": nofault["anchor_version"],
        "straggler": {
            "rank": straggler_rank,
            "slowdown_s_per_step": 0.03,
            "healthy_items_per_sec": round(_tput(straggler, healthy), 1),
            "nofault_items_per_sec": round(_tput(nofault, healthy), 1),
            "straggler_named_by_skew": skew.get("max_rank") == straggler_rank,
            "step_skew_s": skew.get("skew_s"),
            "staleness_events": sum(
                1 for e in straggler["server"]["events"]
                if e[0] == "staleness_exceeded"
            ),
            "accuracy": round(straggler["eval"]["accuracy"], 4),
        },
        "kill_rejoin": {
            "kill_step": kill_step,
            "evictions": kill["server"]["evictions"],
            "rejoins": kill["server"]["rejoins"],
            "crashes": killed["crashes"],
            "completed": killed["completed"],
            "accuracy": round(kill["eval"]["accuracy"], 4),
            "acc_delta_vs_nofault": round(
                kill["eval"]["accuracy"] - acc, 4
            ),
        },
    }


def bench_gpt2_fleet(
    prompt_len: int = 16,
    max_new: int = 48,
    requests: int = 16,
    decode_counts: tuple = (1, 2),
    slots: int = 4,
    max_len: int = 96,
):
    """The disaggregated serving fleet's throughput record (ISSUE 19):
    router + 1 prefill worker + a swept number of decode workers on the
    compat layer, the SAME seeded request set at every point, KV pages
    shipped prefill → decode over ``Comm_dup("fleet-kv")``.

    Record line: ``fleet_req_per_s`` (the headline — requests completed
    per wall second at the LARGEST decode count) and ``workers`` (the
    compact topology stamp, e.g. ``"1p+2d"``, without which the rate is
    uninterpretable). The per-decode-count curve, the scaling ratio vs
    the single-decode point, shipment byte totals and the liveness
    counters are detail-only.

    Each worker's engine is pinned to its OWN device (``rank %
    n_devices``) — the disaggregation analogue: a fleet exists because
    every worker owns an accelerator, and two engines sharing one
    device would serialize in the XLA execution stream by
    construction. The scaling claim is only measurable where that
    pinning buys real parallel silicon: on the CPU simulator the
    decode workers' ticks still serialize on the host (one GIL for
    every dispatch, one shared XLA host threadpool for every fake
    device), so ``req_per_s_scaling`` honestly reads ~1.0 there — a
    measured fact about this host, platform-labeled via the record's
    top-level ``platform``, never extrapolated into a fabricated
    multi-chip figure (roofline honesty rule). Wall time includes each
    worker's engine build; the compiles are paid ONCE up front
    (``warm_engine`` per device + the persistent compile cache) so
    every point replays them identically and the curve compares fleet
    topology, not the compiler.
    """
    import numpy as np

    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.serve import Engine, Request, run_fleet, warm_engine

    cfg = GPT2Config.tiny(
        vocab_size=512, max_seq_len=max_len, num_layers=4, num_heads=4,
        d_model=256,
    )
    params = jax.jit(GPT2(cfg).init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    devices = jax.devices()

    def factory(role, rank):
        dev = devices[max(rank, 0) % len(devices)]
        with jax.default_device(dev):
            return Engine(
                cfg, jax.device_put(params, dev), slots=slots,
                max_len=max_len, prefill_len=prompt_len,
            )

    rng = np.random.RandomState(0)
    reqs = [
        Request(
            rid=f"f{i}",
            prompt=[int(t) for t in rng.randint(1, cfg.vocab_size,
                                                size=prompt_len)],
            max_new_tokens=max_new,
        )
        for i in range(requests)
    ]

    # Pay every device's compiles ONCE before any timed point: engines
    # are per-rank and each rank pins its own device, so warm the
    # LARGEST topology's worth of workers (prefill rank 1, decode
    # ranks 2..1+max). Timed points then replay cached executables and
    # the curve compares fleet topology, not the compiler.
    for rank in range(1, 2 + max(decode_counts)):
        warm_engine(factory("warmup", rank))

    curve = {}
    ship_bytes = evictions = 0
    for d in decode_counts:
        t0 = time.perf_counter()
        res = run_fleet(factory, reqs, prefill=1, decode=d)
        wall = time.perf_counter() - t0
        done = len(res["completed"])
        if done != requests:
            raise RuntimeError(
                f"fleet bench point decode={d} completed {done}/{requests}"
            )
        curve[str(d)] = {
            "req_per_s": round(done / wall, 2),
            "wall_s": round(wall, 2),
        }
        ship_bytes = sum(
            w.get("ship_bytes", 0) for w in res["workers"]
            if w["role"] == "prefill"
        )
        evictions = res["router"]["evictions"]
    d_top = str(max(decode_counts))
    d_one = str(min(decode_counts))
    return {
        "fleet_req_per_s": curve[d_top]["req_per_s"],
        "workers": f"1p+{d_top}d",
        "req_per_s_scaling": round(
            curve[d_top]["req_per_s"] / curve[d_one]["req_per_s"], 3
        ),
        "by_decode_workers": curve,
        "requests": requests,
        "generated_tokens": requests * max_new,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new,
        "ship_bytes": ship_bytes,
        "evictions": evictions,
    }


def _phase_breakdown(s: dict) -> dict:
    """Per-workload obs roll-up for BENCH_DETAIL.json (never the record
    line — ``_LINE_KEYS`` whitelists what rides there): where the
    workload's wall clock went, plus the top collectives by modeled
    wire bytes from the trace-time accounting in comm/collectives.
    ``s`` is the workload's ``Recorder.summary()`` (computed once in
    main, shared with the obs_baseline snapshot)."""
    out = {
        name: {"count": p["count"], "total_s": round(p["total_s"], 3)}
        for name, p in s["phases"].items()
    }
    if s["collectives"]:
        out["top_collectives"] = [
            {**c, "wire_bytes": round(c["wire_bytes"], 1)}
            for c in s["collectives"]
        ]
    return out


# ---------------------------------------------------------------------------
# Driver-contract record building (unit-tested: tests/test_bench_contract.py)
# ---------------------------------------------------------------------------

# Per-workload keys that ride ON THE LINE; everything else detail-file-only.
_LINE_KEYS = {
    # app_path_images_per_sec is byte-for-byte the record's headline
    # ``value`` — dropped from the per-workload detail (with gpt2's
    # derivable vs_r1_app_path) to pay for ISSUE 7's serve triple
    # inside the ≤1.2k budget; BENCH_DETAIL.json keeps the full dict.
    # mfu_pct (ISSUE 8): the train workloads' utilization verdict rides
    # the line (null off-TPU — platform-labeled in the detail file's
    # roofline block, never fabricated); the full measured-vs-modeled
    # roofline table stays detail-only. To hold the ≤1.2k budget,
    # ms_per_step moved detail-only everywhere — it is EXACTLY
    # derivable from the line (ms_per_step = items_per_step /
    # items_per_sec × 1e3, both already on the line).
    # ISSUE 11 pays for the mnist_easgd triple by moving more
    # derivable/static echo detail-only: alexnet's global_batch and
    # gpt2/gpt2_moe's batch + seq_len (fixed workload geometry),
    # gpt2's app_path_tokens_per_sec (EXACTLY tokens_per_sec x
    # (1 - app_path_overhead_pct/100), both still on the line), and
    # gpt2_moe's final_loss (in BENCH_DETAIL.json verbatim, with the
    # whole drop-rate trajectory).
    # ISSUE 12 pays for gpt2_policy's triple by moving the remaining
    # train-workload final_loss echoes detail-only (gpt2_moe's went in
    # ISSUE 11; the convergence pins live in tests and the values land
    # in BENCH_DETAIL.json verbatim), gpt2_serve's kv_page_size (static
    # geometry) and gpt2_slo's ttft_target_s (the sweep's calibration
    # context — headline + breach count keep the verdict on the line).
    "alexnet": (
        "images_per_sec", "mfu_pct",
        "error",
    ),
    # To pay for ISSUE 9's allreduce pair inside the ≤1.2k budget,
    # static config echo moved detail-only: resnet50's global_batch and
    # gpt2's seq_len (both fixed workload geometry, in BENCH_DETAIL.json
    # verbatim), plus the allreduce entry's devices (byte-for-byte the
    # record's top-level detail.devices).
    "resnet50": (
        "images_per_sec", "mfu_pct",
        "error",
    ),
    # fleet_req_per_s + workers (ISSUE 19): the disaggregated fleet's
    # throughput headline and the topology stamp that makes it
    # readable. Paid for by demoting gpt2's train-side "attention"
    # label (static engine config — the flash-vs-reference resolution
    # is pinned per-platform by tier-1's fallback tests, the same
    # argument that moved decode_attention off the serve line for
    # ISSUE 17; verbatim in BENCH_DETAIL.json) and gpt2_serve's
    # max_concurrent_at_hbm (the MODELED fixed-budget concurrency
    # experiment — ISSUE 18's measured hbm_held_peak_bytes +
    # kv_headroom_min_pct are the line's capacity verdict now; the
    # experiment stays verbatim in the paged_capacity detail block
    # where its A/B context lives).
    "gpt2": (
        "tokens_per_sec",
        "app_path_overhead_pct", "mfu_pct",
        "error",
    ),
    "gpt2_moe": (
        "tokens_per_sec", "mfu_pct",
        "error",
    ),
    # ISSUE 7 grows the serve line by the paged-cache headline triple:
    # max concurrent requests at the fixed HBM budget, the prefix-hit
    # rate behind it, and the page size defining both; the capacity and
    # chunked-prefill blocks stay detail-only.
    # engine_compiles (ISSUE 8): the pinned engine-lifetime compile
    # count. To pay for it, latency_p50_s (the SLO-relevant p95 stays)
    # and the static slots geometry moved detail-only.
    # accepted_tokens_per_tick (ISSUE 13): the speculative tokens-per-
    # slot-tick multiplier from the A/B block (1.0 = plain decode);
    # paid for by demoting decode_hbm_util_pct detail-only — it is
    # EXACTLY derivable from detail keys (decode_hbm_gbps_modeled /
    # the roofline_platform chip's HBM peak; null off-TPU anyway).
    # kv_dtype + q8_capacity_ratio (ISSUE 15): the headline stream's
    # cache wire dtype (bandwidth/capacity figures are uninterpretable
    # without it) and the int8-vs-bf16 concurrency ratio at the same
    # pool HBM budget; paid for by demoting latency_p95_s (the
    # SLO-relevant p95 verdicts live on the gpt2_slo/gpt2_policy
    # lines) and engine_compiles (its value is PINNED to the engine's
    # lifetime constant by tier-1 — tests/test_serve.py — so the line
    # key carried no information; BENCH_DETAIL.json keeps it verbatim
    # and an unexpected recompile still fails the suite) detail-only.
    # trace_overhead_pct + exemplars_retained (ISSUE 16): the request-
    # ledger's aggregate-arm decode cost (the always-on production
    # config — the acceptance bar is <1%, and the line is where that
    # verdict must be readable) and the exemplar count proving tail
    # capture ran; the forensics snapshot (why-slow's input) is
    # detail-only. Paid for by demoting prefix_hit_rate (the mechanism
    # BEHIND max_concurrent_at_hbm, which keeps the capacity verdict on
    # the line) and kv_dtype (static engine config, pinned by tier-1 —
    # the q8 ratio already names the comparison) — both verbatim in
    # BENCH_DETAIL.json.
    # weights_dtype + q8w_bytes_ratio (ISSUE 17): the headline stream's
    # weight wire dtype (the param read DOMINATES the decode tick, so
    # byte figures are uninterpretable without it) and the modeled
    # int8-vs-f32 whole-tick decode-bytes ratio from the weights A/B.
    # Paid for by demoting decode_attention (static engine config — the
    # kernel-vs-reference resolution is pinned per-platform by tier-1's
    # fallback tests and lands in BENCH_DETAIL.json verbatim, so
    # ISSUE 5's attributability survives in the detail file) and
    # exemplars_retained (its ≥1 pin lives in the artifact test —
    # TestForensicsArtifact — and trace_overhead_pct keeps the ledger
    # verdict on the line) — both verbatim in BENCH_DETAIL.json.
    # hbm_held_peak_bytes + kv_headroom_min_pct (ISSUE 18): the memory
    # ledger's MEASURED held-bytes peak for the headline stream and the
    # KV headroom floor it bottomed out at — the capacity verdict is
    # now byte-exact accounting, not a model. Paid for by demoting
    # the MODELED byte projections the measured ledger supersedes —
    # q8_capacity_ratio and q8w_bytes_ratio (both verbatim in their
    # quantized_kv / quantized_weights detail blocks, where the A/B
    # context that makes them interpretable lives) — plus
    # weights_dtype (static engine config pinned by tier-1, verbatim
    # in BENCH_DETAIL.json).
    "gpt2_serve": (
        "decode_tokens_per_sec",
        "accepted_tokens_per_tick",
        "hbm_held_peak_bytes", "kv_headroom_min_pct",
        "trace_overhead_pct", "error",
    ),
    # The SLO sweep's line is the headline triple only — the sustained
    # rate, the target that defines it, and the breach count proving the
    # ladder actually crossed saturation; the curve, calibration,
    # geometry and engine mode are detail-file-only (the ≤1.2k budget
    # holds with margin; gpt2_moe's dispatch label and gpt2_serve's
    # request count moved detail-only to pay for it — every full dict
    # still lands in BENCH_DETAIL.json verbatim).
    "gpt2_slo": (
        "max_sustained_req_per_s", "slo_breaches",
        "error",
    ),
    # ISSUE 12: the policy A/B's headline pair — max sustained req/s
    # under the POLICY at p95 interactive TTFT ≤ target (the FIFO
    # counterpart it must beat sits in detail) and the policy's
    # interactive-tier p95 at the top swept rate. Curve, calibration,
    # geometry, target and the FIFO numbers are detail-file-only; the
    # budget payment is itemized above the alexnet entry.
    # tiering (ISSUE 20): the HBM→host A/B's verdict object — p95
    # resume-via-restream vs resume-via-recompute on the drained
    # long-tail trace, and the prefix hit rate the host tier held up
    # under pool pressure ("hit_rate"; the untiered counterpart and
    # the byte/counter evidence live in tiering_detail). Paid for by
    # demoting preemptions (a non-null restream_p95_ms REQUIRES the
    # preempt→park→resume path to have run, so the count's
    # proof-of-work role is subsumed; verbatim per-point in detail),
    # alexnet's app_path_overhead_pct (EXACTLY derivable on the line:
    # 100 × (1 − record.value / alexnet.images_per_sec)) and the
    # allreduce ring_gbps (off-TPU it is byte-identical to gbps by the
    # shared ring model; the measured-vs-stock comparison lives in the
    # by_payload_mb detail curve — q8_gbps, the figure with its own
    # information, stays).
    "gpt2_policy": (
        "max_sustained_req_per_s_policy", "interactive_ttft_p95_ms",
        "tiering", "error",
    ),
    # ISSUE 9: the ring and quantized-ring figures ride the line next to
    # the stock one (modeled off-TPU — the `modeled` flag labels all
    # three); the per-payload three-variant curve stays detail-only.
    "allreduce": ("gbps", "q8_gbps", "modeled", "error"),
    # ISSUE 11: the elastic tier's robustness triple — accuracy parity
    # with sync SPMD, healthy-replica throughput under an injected
    # straggler, and steps re-trained after a kill+rejoin. Fleet/fault
    # geometry and the per-scenario evidence blocks are detail-only.
    "mnist_easgd": (
        "easgd_acc_delta_vs_sync", "straggler_healthy_throughput_pct",
        "rejoin_steps_to_recover", "error",
    ),
    # ISSUE 19: the fleet headline + topology stamp only (budget
    # payment itemized above the gpt2 entry); the per-decode-count
    # curve, scaling ratio, shipment bytes and liveness counters are
    # detail-file-only.
    "gpt2_fleet": ("fleet_req_per_s", "workers", "error"),
}


def build_record(results: dict, pending=(), truncated=(), elapsed_s=None,
                 baselines=None):
    """The compact driver record: headline + per-workload essentials.

    ``results`` maps workload name → the full dict its bench_* returned
    (absent = not run). The full dicts belong in BENCH_DETAIL.json; this
    record is the ≤1,200-char line. Pure function of its inputs so the
    contract test can pin the line length with canned numbers.
    """
    r1_alex, r1_gpt2 = baselines if baselines else _round1_baselines()
    detail = {}
    for name, keys in _LINE_KEYS.items():
        if name in results:
            full = results[name]
            detail[name] = {k: full[k] for k in keys if k in full}
    gpt2 = detail.get("gpt2")
    if gpt2 and "tokens_per_sec" in gpt2:
        gpt2["vs_r1"] = round(gpt2["tokens_per_sec"] / r1_gpt2, 3)
    alex = results.get("alexnet", {})
    value = alex.get("app_path_images_per_sec")
    rec = {
        # Headline = the APP-PATH number (round-3 verdict item 10): what
        # the training loop actually delivers, one host dispatch per step.
        # vs_baseline keeps the round-1 scanned recording as denominator
        # (the only cross-round constant): "app path now vs headline then".
        "metric": "alexnet_imagenet_app_path_images_per_sec",
        "value": value,
        "unit": "images/sec",
        "vs_baseline": round(value / r1_alex, 3) if value else None,
        "detail": detail,
    }
    if elapsed_s is not None:
        rec["elapsed_s"] = round(elapsed_s, 1)
    if pending:
        rec["pending"] = list(pending)
    if truncated:
        rec["truncated"] = list(truncated)
    rec["detail_file"] = "BENCH_DETAIL.json"
    return rec


class _Emitter:
    """Writes BENCH_DETAIL.json + prints the compact line after every
    completed workload, so a driver kill at ANY point leaves the last
    complete record inside its 2,000-char tail window."""

    def __init__(self, t0: float):
        self.t0 = t0
        self.results: dict = {}
        self.truncated: list = []
        self.platform = jax.devices()[0].platform
        self.devices = jax.device_count()
        # emit() runs on BOTH the main thread (per-workload) and the
        # watchdog timer thread (timeout path); without mutual exclusion
        # the two interleave the BENCH_DETAIL.json rename with the final
        # record print (round-5 advisor finding). One lock serializes
        # whole emissions; the last writer's line is last in the tail.
        self._lock = threading.Lock()

    def emit(self, pending=(), lock_timeout=None):
        """``lock_timeout`` (watchdog path): best-effort acquire so a
        main thread wedged INSIDE _emit_locked (stalled stdout pipe,
        hung filesystem) cannot keep the watchdog from its os._exit —
        the wedged emitter's already-printed line is the record then."""
        if lock_timeout is None:
            with self._lock:
                return self._emit_locked(pending)
        if self._lock.acquire(timeout=lock_timeout):
            try:
                return self._emit_locked(pending)
            finally:
                self._lock.release()
        return None

    def _emit_locked(self, pending=()):
        elapsed = time.perf_counter() - self.t0
        rec = build_record(
            self.results, pending=pending, truncated=self.truncated,
            elapsed_s=elapsed,
        )
        rec["detail"]["devices"] = self.devices
        rec["detail"]["platform"] = self.platform
        try:
            # tmp + atomic rename (same pattern as train/checkpoint.py's
            # run_meta): a watchdog os._exit mid-dump must never leave a
            # half-written file where the record line points.
            path = os.path.join(_REPO, "BENCH_DETAIL.json")
            with open(path + ".tmp", "w") as f:
                json.dump(
                    {
                        "elapsed_s": round(elapsed, 1),
                        "devices": self.devices,
                        "platform": self.platform,
                        "pending": list(pending),
                        "truncated": self.truncated,
                        "workloads": self.results,
                    },
                    f,
                    indent=1,
                )
            os.replace(path + ".tmp", path)
        except OSError as e:
            rec["detail_file_error"] = str(e)[:80]
        line = json.dumps(rec)
        print(line, flush=True)
        return line


def main():
    _enable_compile_cache()  # before the first trace (see its docstring)
    t0 = time.perf_counter()
    budget = float(os.environ.get("MPIT_BENCH_BUDGET_S", "420"))
    em = _Emitter(t0)

    # Headline-first ordering; each entry = (name, fn). The modeled
    # allreduce figure is free, so it rides along from the start.
    workloads = [
        ("allreduce", bench_allreduce),
        ("alexnet", bench_alexnet),
        ("gpt2", bench_gpt2),
        ("resnet50", bench_resnet),
        ("gpt2_moe", bench_moe),
        ("gpt2_serve", bench_gpt2_serve),
        ("gpt2_slo", bench_gpt2_slo),
        ("gpt2_policy", bench_gpt2_policy),
        ("mnist_easgd", bench_mnist_easgd),
        ("gpt2_fleet", bench_gpt2_fleet),
    ]

    def _watchdog():
        # Hard stop: force out the record-so-far and exit clean — runs
        # on a daemon thread so it fires even while the main thread is
        # blocked in a GIL-RELEASING native call (XLA compiles and
        # device fetches, the two ways a workload actually gets stuck
        # here). A native loop that held the GIL would still block it,
        # but then nothing in-process could run; progressive emission
        # (the already-printed lines in the driver's tail) is the
        # backstop for that case.
        try:
            remaining = [n for n, _ in workloads if n not in em.results]
            em.truncated.extend(
                n for n in remaining if n not in em.truncated
            )
            em.emit(lock_timeout=15.0)
        finally:
            # Exit unconditionally: an emit() error here (e.g. a dict
            # mutated concurrently by the main thread) must not leave
            # the process alive past the driver's timeout.
            os._exit(0)

    watchdog = threading.Timer(budget * 1.2 + 30, _watchdog)
    watchdog.daemon = True
    watchdog.start()

    from mpit_tpu import obs

    for i, (name, fn) in enumerate(workloads):
        elapsed = time.perf_counter() - t0
        if elapsed > budget:
            em.truncated.extend(n for n, _ in workloads[i:])
            break
        t_w = time.perf_counter()
        # Fresh recorder per workload: the phase breakdown attached to
        # BENCH_DETAIL.json covers exactly this workload's events
        # (staging/warmup/timed windows + trace-time collective bytes).
        rec = obs.enable(obs.Recorder())
        try:
            with obs.span("workload", workload=name):
                em.results[name] = fn()
        except Exception as e:  # one workload must not kill the artifact
            em.results[name] = {
                "error": f"{type(e).__name__}: {e}"[:200]
            }
        # Wall seconds the workload took end to end (compile + staging +
        # measurement) — the time-budget diagnostic; detail-file only.
        em.results[name]["wall_s"] = round(time.perf_counter() - t_w, 1)
        summ = rec.summary(top_collectives=3)
        em.results[name]["phases"] = _phase_breakdown(summ)
        # Perf-regression gate input (ISSUE 3; obs/baseline.py): the
        # full per-phase snapshot (count/total/p50/p95) in the shape
        # `python -m mpit_tpu.obs diff BENCH_DETAIL.json <new> --workload
        # <name>` consumes — so two bench rounds diff mechanically.
        # Only for workloads that actually MEASURED: an errored one
        # would snapshot just its enclosing 'workload' span, and a
        # later diff against that gate-passes vacuously (every real
        # phase lands in new_phases, which is reported, not gated).
        if "error" not in em.results[name]:
            em.results[name]["obs_baseline"] = obs.baseline.snapshot(
                summ, meta={"workload": name},
                # ISSUE 18: memory-gate input — held_peak_bytes +
                # headroom floor ride the baseline so two bench rounds
                # diff memory growth mechanically (only stored when the
                # workload actually carried ledger data; never gates
                # vacuously).
                memory=em.results[name].get("memory"),
            )
        em.emit(pending=[n for n, _ in workloads[i + 1:]])

    obs.disable()
    watchdog.cancel()
    em.emit()


if __name__ == "__main__":
    main()
