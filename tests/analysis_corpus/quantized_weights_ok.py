"""Corpus: the per-block dequant discipline passes the
quantized-weights contract (ISSUE 17) — the false-positive guard for
``quantized_weights_bad.py``.

``project`` contracts the same int8 kernel one ROW-block at a time:
each iteration dequantizes one [block, F] tile (the block's int8 rows
times their scales) and accumulates its partial product, so the largest
f32 kernel-shaped intermediate is ``[block, F]``, never ``[D, F]``.
This is the shape of the real blocked matmul
(:func:`mpit_tpu.ops.quantized_matmul.quantized_matmul_lax`); the
kernel-shaped f32 aval the contract hunts must NOT appear. No static
rule fires here.
"""

import jax.numpy as jnp

from mpit_tpu.ops.ring_collectives import dequantize_blocks

ROWS, COLS = 32, 96
BLOCK = 8


def project(x, w_q, w_scale, bias):
    """x [B, D] against an int8 kernel [D, F] + per-row scales [D, 1],
    dequantized per row-block — the clean idiom."""
    d = w_q.shape[0]
    acc = jnp.zeros((x.shape[0], w_q.shape[1]), jnp.float32)
    for i in range(0, d, BLOCK):
        w_tile = dequantize_blocks(
            w_q[i : i + BLOCK], w_scale[i : i + BLOCK]
        )  # [BLOCK, F] f32 — tile-sized, the allowed grain
        acc = acc + jnp.einsum("bd,df->bf", x[:, i : i + BLOCK], w_tile)
    return acc + bias
