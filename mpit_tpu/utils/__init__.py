"""mpit_tpu.utils — observability and accounting utilities.

Where the reference's observability is per-rank prints and ad-hoc wall
timers in its Lua scripts (SURVEY.md §6), this package provides the
TPU-native toolkit: profiler traces, blocking step timers, XLA cost
analysis, roofline estimates, and collective-traffic models.
"""

from mpit_tpu.utils.aot import (
    abstract_state,
    abstractify,
    aot_compile,
    memory_report,
    topology_devices,
    topology_world,
)
from mpit_tpu.utils.profiling import (
    ChipSpec,
    CommModel,
    StepTimer,
    TPU_V5E,
    allreduce_gbps,
    collective_bytes,
    compiled_cost,
    modeled_all_gather_seconds,
    modeled_allreduce_seconds,
    modeled_reduce_scatter_seconds,
    roofline,
    scaling_projection,
    trace,
    tree_bytes,
)

__all__ = [
    "abstract_state",
    "abstractify",
    "aot_compile",
    "memory_report",
    "topology_devices",
    "topology_world",
    "ChipSpec",
    "CommModel",
    "StepTimer",
    "TPU_V5E",
    "allreduce_gbps",
    "collective_bytes",
    "compiled_cost",
    "modeled_all_gather_seconds",
    "modeled_allreduce_seconds",
    "modeled_reduce_scatter_seconds",
    "roofline",
    "scaling_projection",
    "trace",
    "tree_bytes",
]
