"""ISSUE 18: the HBM memory ledger — byte-exact capacity accounting.

Every HBM-holding subsystem of the serve stack registers with ONE
:class:`MemLedger` and reports its allocation lifecycle as ``grant`` /
``free`` events, so that at any tick :meth:`MemLedger.held` decomposes
total device memory into attributed components and the conservation
invariant — ``granted − freed == held``, per subsystem and in total —
holds exactly. The obs tiers before this one observe *time* (spans,
stream windows), *work* (roofline bytes/FLOPs moved), and *causality*
(the request ledger); this layer observes bytes **held**, the signal
the capacity claims (paged KV, int8 KV, int8 weights) were previously
modeling with bench arithmetic alone, and the signal the fleet router
(ROADMAP item 1: per-worker headroom) and HBM→host tiering (ROADMAP
item 3: ranked cold-page inventory) both block on.

Layout convention (the serve stack's registration, ``serve.engine``):

- **top-level subsystems** hold real device buffers and sum into
  ``held()``: ``weights`` (the target param store, int8 q + f32 scale
  blocks counted at wire width), ``draft_weights`` (0 bytes when the
  draft aliases target leaves via ``draft_from_target``; real bytes
  when separately quantized), ``kv_pool`` (the cache buffers — target
  + draft, K and V, lengths arrays), ``step_buffers`` (per-slot decode
  state);
- **nested subsystems** (``nested_in=``) decompose a parent's capacity
  without double-counting into the total: ``kv_pages`` tracks physical
  page occupancy inside ``kv_pool`` (grants at free-list pops, frees
  at refcount-zero returns) and ``kv_cow_reserve`` tracks the pages
  the allocator holds back for copy-on-write divergence. Headroom =
  ``kv_pages`` capacity − ``kv_pages`` held − ``kv_cow_reserve`` held
  == free grantable pages × page bytes, exactly.

The roofline honesty rule applies throughout (ISSUE 8): ledger numbers
are *modeled wire bytes* and always carry the platform label;
:meth:`reconcile` reads ``device.memory_stats()`` only when the
platform IS the TPU — off-TPU it reports the ledger bytes, the
platform, and ``None`` device bytes, never a fabricated measurement.

Import-light like the rest of :mod:`mpit_tpu.obs`: no jax, no numpy —
the ledger is pure host arithmetic and importable from anywhere
(``serve.kvcache`` is imported by the engine before jax arrays exist).

``python -m mpit_tpu.obs capacity`` (see :func:`capacity_report` /
:func:`format_capacity`) is the offline verdict over a snapshot — the
why-slow exit grammar: 0 on a usable verdict, 2 on input without
ledger data (a capacity verdict over a snapshot that never measured
bytes would be fiction, not zero).
"""

from __future__ import annotations

MEMLEDGER_FORMAT = "mpit-obs-memledger-v1"

#: Reconciliation tolerance (%): jax's allocator rounds buffers up and
#: holds runtime scratch the wire model deliberately excludes.
DEFAULT_RECONCILE_TOLERANCE_PCT = 10.0


class MemLedger:
    """Byte-exact device-memory ledger (see module docstring).

    All byte quantities are integral and < 2^53, so float accumulation
    is exact; the invariant checks compare with ``==``, not a
    tolerance. Grants/frees from unregistered subsystems auto-register
    (top-level, no capacity) so instrumentation never KeyErrors on an
    engine variant that skipped a registration.
    """

    def __init__(self, *, platform: str = "unknown"):
        self.platform = platform
        # subsystem -> {held, granted, freed, grants, frees, peak,
        #               capacity, nested_in, meta}
        self._subs: dict[str, dict] = {}
        # owner (rid) -> {tenant, last_touch, state} — the eviction
        # ranking's recency index. Owners are forgotten at retire so
        # the registry tracks residents, not history.
        self._owners: dict[str, dict] = {}
        self._peak = 0.0
        self._peak_tick = 0
        self._exhaustion: dict | None = None
        self.exhaustions = 0

    # -- registration --------------------------------------------------------
    def register(
        self,
        subsystem: str,
        *,
        capacity_bytes: float | None = None,
        nested_in: str | None = None,
        **meta,
    ) -> None:
        """Declare a subsystem (idempotent; re-register updates
        capacity/meta without touching the accumulators). ``nested_in``
        marks it as a decomposition of a parent subsystem: its held
        bytes do NOT add into :meth:`held`'s total."""
        sub = self._subs.get(subsystem)
        if sub is None:
            sub = self._subs[subsystem] = {
                "held": 0.0, "granted": 0.0, "freed": 0.0,
                "grants": 0, "frees": 0, "peak": 0.0,
                "capacity": None, "nested_in": None, "meta": {},
            }
        if capacity_bytes is not None:
            sub["capacity"] = float(capacity_bytes)
        if nested_in is not None:
            sub["nested_in"] = nested_in
        if meta:
            sub["meta"].update(meta)

    # -- the lifecycle events ------------------------------------------------
    def grant(
        self,
        subsystem: str,
        nbytes: float,
        *,
        owner=None,
        tenant: str | None = None,
        tick: int | None = None,
        kind: str | None = None,
    ) -> None:
        """Record ``nbytes`` newly held by ``subsystem``. ``owner`` /
        ``tenant`` / ``tick`` annotate the owner registry for the
        eviction ranking; attribution totals are computed at query
        time from allocator ground truth, never accumulated here (no
        drift)."""
        if nbytes < 0:
            raise ValueError(f"grant of negative bytes: {nbytes}")
        sub = self._subs.get(subsystem)
        if sub is None:
            self.register(subsystem)
            sub = self._subs[subsystem]
        sub["held"] += nbytes
        sub["granted"] += nbytes
        sub["grants"] += 1
        if sub["held"] > sub["peak"]:
            sub["peak"] = sub["held"]
        if sub["nested_in"] is None:
            total = self.held()
            if total > self._peak:
                self._peak = total
                self._peak_tick = int(tick or 0)
        if owner is not None:
            self.touch(owner, tick=tick or 0, tenant=tenant, state=kind)

    def free(
        self,
        subsystem: str,
        nbytes: float,
        *,
        owner=None,
        tick: int | None = None,
        kind: str | None = None,
    ) -> None:
        """Record ``nbytes`` returned by ``subsystem``. Over-freeing
        (held going negative) is an instrumentation bug, surfaced by
        :meth:`conservation`, not silently clamped."""
        if nbytes < 0:
            raise ValueError(f"free of negative bytes: {nbytes}")
        sub = self._subs.get(subsystem)
        if sub is None:
            self.register(subsystem)
            sub = self._subs[subsystem]
        sub["held"] -= nbytes
        sub["freed"] += nbytes
        sub["frees"] += 1

    # -- the owner recency index ---------------------------------------------
    def touch(
        self, owner, *, tick: int,
        tenant: str | None = None, state: str | None = None,
    ) -> None:
        """Update ``owner``'s last-touch tick (monotonic max) — the
        recency signal the eviction ranking orders by."""
        e = self._owners.setdefault(
            owner, {"tenant": tenant, "last_touch": int(tick), "state": state}
        )
        e["last_touch"] = max(e["last_touch"], int(tick))
        if tenant is not None:
            e["tenant"] = tenant
        if state is not None:
            e["state"] = state

    def forget(self, owner) -> None:
        """Drop a retired owner from the recency index."""
        self._owners.pop(owner, None)

    def reset_transients(self) -> None:
        """Forget owner recency and exhaustion forensics (an engine
        reset between runs). Byte accumulators are NOT touched — the
        buffers persist across resets and the conservation history
        must cover their whole lifetime."""
        self._owners.clear()
        self._exhaustion = None
        self.exhaustions = 0

    def owners(self) -> dict:
        return {k: dict(v) for k, v in self._owners.items()}

    # -- queries -------------------------------------------------------------
    def held(self, subsystem: str | None = None) -> float:
        """Bytes currently held — by one subsystem, or (default) the
        total over top-level subsystems (nested decompositions are a
        view into their parent, not additional memory)."""
        if subsystem is not None:
            sub = self._subs.get(subsystem)
            return sub["held"] if sub is not None else 0.0
        return sum(
            s["held"] for s in self._subs.values()
            if s["nested_in"] is None
        )

    def decompose(self) -> dict:
        """``{subsystem: held_bytes}`` over every registered subsystem
        (nested included — the reader distinguishes via snapshot's
        ``nested_in``)."""
        return {
            name: int(sub["held"]) for name, sub in sorted(self._subs.items())
        }

    def capacity(self, subsystem: str) -> float | None:
        sub = self._subs.get(subsystem)
        return sub["capacity"] if sub is not None else None

    def headroom(self, subsystem: str) -> float | None:
        """``capacity − held`` for one subsystem; None when it never
        declared a capacity (headroom against an unknown ceiling would
        be a fabricated number)."""
        sub = self._subs.get(subsystem)
        if sub is None or sub["capacity"] is None:
            return None
        return sub["capacity"] - sub["held"]

    def watermark(self) -> dict:
        """Peak total held bytes, the tick it was set, and per-subsystem
        peaks."""
        return {
            "held_peak_bytes": int(self._peak),
            "tick": self._peak_tick,
            "subsystems": {
                name: int(sub["peak"])
                for name, sub in sorted(self._subs.items())
            },
        }

    def conservation(self) -> dict:
        """The invariant: per subsystem ``granted − freed == held`` and
        ``held >= 0``, compared EXACTLY (integral floats). ``ok`` is
        the conjunction; per-subsystem verdicts name the violator."""
        subs = {}
        ok = True
        for name, sub in sorted(self._subs.items()):
            sub_ok = (
                sub["granted"] - sub["freed"] == sub["held"]
                and sub["held"] >= 0
            )
            ok = ok and sub_ok
            subs[name] = {
                "ok": sub_ok,
                "granted_bytes": int(sub["granted"]),
                "freed_bytes": int(sub["freed"]),
                "held_bytes": int(sub["held"]),
            }
        return {"ok": ok, "total_held_bytes": int(self.held()),
                "subsystems": subs}

    # -- exhaustion forensics ------------------------------------------------
    def note_exhaustion(self, dump: dict) -> None:
        """Retain the most recent pool-exhaustion forensics dump (the
        ranked top-holders table the scheduler builds at the
        ``kv_pool_exhausted`` edge) for the end-of-run snapshot."""
        self._exhaustion = dict(dump)
        self.exhaustions += 1

    # -- reconciliation ------------------------------------------------------
    def reconcile(
        self, device=None, *,
        tolerance_pct: float = DEFAULT_RECONCILE_TOLERANCE_PCT,
    ) -> dict:
        """Compare ledger-held bytes against the device allocator's
        view. ONLY on the real chip: off-TPU the report carries the
        platform label, the ledger bytes, and ``device_bytes: None`` —
        the roofline honesty rule; a CPU process's RSS is not HBM."""
        out = {
            "platform": self.platform,
            "ledger_bytes": int(self.held()),
            "device_bytes": None,
            "delta_pct": None,
            "within_tolerance": None,
            "tolerance_pct": tolerance_pct,
        }
        if self.platform != "tpu" or device is None:
            return out
        stats_fn = getattr(device, "memory_stats", None)
        stats = stats_fn() if callable(stats_fn) else None
        if not stats or "bytes_in_use" not in stats:
            return out
        dev = float(stats["bytes_in_use"])
        out["device_bytes"] = int(dev)
        delta = 100.0 * abs(dev - out["ledger_bytes"]) / max(dev, 1.0)
        out["delta_pct"] = round(delta, 2)
        out["within_tolerance"] = delta <= tolerance_pct
        return out

    # -- snapshot ------------------------------------------------------------
    def snapshot(self) -> dict:
        """The serializable whole-ledger view (BENCH_DETAIL / baseline
        food). Conservation is evaluated at snapshot time so a stored
        snapshot carries its own verdict."""
        subs = {}
        for name, sub in sorted(self._subs.items()):
            e = {
                "held_bytes": int(sub["held"]),
                "granted_bytes": int(sub["granted"]),
                "freed_bytes": int(sub["freed"]),
                "grants": sub["grants"],
                "frees": sub["frees"],
                "peak_bytes": int(sub["peak"]),
            }
            if sub["capacity"] is not None:
                e["capacity_bytes"] = int(sub["capacity"])
            if sub["nested_in"] is not None:
                e["nested_in"] = sub["nested_in"]
            if sub["meta"]:
                e["meta"] = dict(sub["meta"])
            subs[name] = e
        out = {
            "format": MEMLEDGER_FORMAT,
            "platform": self.platform,
            "held_bytes": int(self.held()),
            "held_peak_bytes": int(self._peak),
            "held_peak_tick": self._peak_tick,
            "subsystems": subs,
            "conservation": self.conservation(),
        }
        if self._owners:
            out["owners"] = self.owners()
        if self._exhaustion is not None:
            out["exhaustion"] = dict(self._exhaustion)
            out["exhaustions"] = self.exhaustions
        return out


# ---------------------------------------------------------------------------
# Offline capacity verdict (``python -m mpit_tpu.obs capacity``).
# ---------------------------------------------------------------------------


def _find_memory_block(doc: dict, workload: str | None = None):
    """Locate the memory block in any of the accepted input shapes:
    a raw :meth:`MemLedger.snapshot`, a ``Server.stats()`` dump (its
    ``memory`` key), that ``memory`` block alone, or a
    ``BENCH_DETAIL.json`` (``workloads`` → serve entries carrying a
    ``memory`` block). Returns ``(block, label)``."""
    if not isinstance(doc, dict):
        raise ValueError("not a JSON object")
    if doc.get("format") == MEMLEDGER_FORMAT:
        return doc, "memledger snapshot"
    mem = doc.get("memory")
    if isinstance(mem, dict) and mem.get("source") == "memledger":
        return mem, "stats dump"
    if doc.get("source") == "memledger":
        return doc, "memory block"
    workloads = doc.get("workloads")
    if isinstance(workloads, dict):
        names = [workload] if workload else sorted(workloads)
        for name in names:
            entry = workloads.get(name)
            if not isinstance(entry, dict):
                continue
            mem = entry.get("memory")
            if isinstance(mem, dict) and mem.get("source") == "memledger":
                return mem, f"workload {name}"
        raise ValueError(
            "no workload in this BENCH_DETAIL carries a memory-ledger "
            "block — re-run the serve bench on a build with ISSUE 18"
        )
    raise ValueError(
        "input carries no memory-ledger data (need a memledger "
        "snapshot, a Server.stats() dump with a 'memory' block, or a "
        "BENCH_DETAIL.json from a serve bench)"
    )


def capacity_report(doc: dict, *, workload: str | None = None) -> dict:
    """Build the capacity verdict from a snapshot document. Raises
    :class:`ValueError` on input without ledger data — the CLI maps
    that to exit 2 (the why-slow grammar: no verdict beats a fabricated
    one)."""
    mem, label = _find_memory_block(doc, workload)
    # Normalize the two block shapes: a raw MemLedger.snapshot carries
    # ``subsystems`` dicts; the Server.stats() memory block carries the
    # flattened ``held_by_subsystem`` plus kv headroom fields.
    if "subsystems" in mem:
        by_sub = {
            name: e.get("held_bytes", 0)
            for name, e in mem["subsystems"].items()
        }
        kv = mem["subsystems"].get("kv_pages", {})
        capacity = kv.get("capacity_bytes")
        reserve = (
            mem["subsystems"].get("kv_cow_reserve", {}).get("held_bytes", 0)
        )
        headroom = (
            capacity - kv.get("held_bytes", 0) - reserve
            if capacity is not None else None
        )
        headroom_pct = (
            round(100.0 * headroom / capacity, 2)
            if capacity else None
        )
        headroom_min_pct = None
        host = mem["subsystems"].get("kv_host_pages", {})
        host_held = host.get("held_bytes") if host else None
        host_cap = host.get("capacity_bytes") if host else None
    else:
        by_sub = dict(mem.get("held_by_subsystem", {}))
        capacity = mem.get("kv_capacity_bytes")
        headroom = mem.get("kv_headroom_bytes")
        headroom_pct = mem.get("kv_headroom_pct")
        headroom_min_pct = mem.get("kv_headroom_min_pct")
        host_held = mem.get("host_held_bytes")
        host_cap = mem.get("host_capacity_bytes")
    conservation = mem.get("conservation", {})
    report = {
        "source": label,
        "platform": mem.get("platform", "unknown"),
        "held_bytes": int(mem.get("held_bytes", 0)),
        "held_peak_bytes": int(
            mem.get("held_peak_bytes", mem.get("held_bytes", 0))
        ),
        "held_by_subsystem": by_sub,
        "kv_capacity_bytes": capacity,
        "kv_headroom_bytes": headroom,
        "kv_headroom_pct": headroom_pct,
        "kv_headroom_min_pct": headroom_min_pct,
        "conservation_ok": bool(conservation.get("ok", False)),
    }
    if host_held is not None:
        # Host KV tier (ISSUE 20): present only when the run carried a
        # tiered pool — a pre-tiering snapshot reports no host line.
        report["host_held_bytes"] = int(host_held)
        if host_cap is not None:
            report["host_capacity_bytes"] = int(host_cap)
        if mem.get("host_held_peak_bytes") is not None:
            report["host_held_peak_bytes"] = int(
                mem["host_held_peak_bytes"]
            )
    if mem.get("reconciliation"):
        report["reconciliation"] = mem["reconciliation"]
    if mem.get("eviction_candidates"):
        report["eviction_candidates"] = mem["eviction_candidates"]
    if mem.get("exhaustion"):
        report["exhaustion"] = mem["exhaustion"]
    return report


def format_capacity(report: dict) -> str:
    """Human-readable capacity verdict (the why-slow formatting idiom:
    a header line, an attribution table, then the verdicts)."""
    lines = [
        f"capacity verdict — platform={report['platform']} "
        f"({report['source']})",
        f"  held {_fmt_bytes(report['held_bytes'])}   "
        f"peak {_fmt_bytes(report['held_peak_bytes'])}",
    ]
    by_sub = report.get("held_by_subsystem", {})
    if by_sub:
        total = max(report["held_bytes"], 1)
        lines.append("  held by subsystem:")
        for name, b in sorted(by_sub.items(), key=lambda kv: -kv[1]):
            lines.append(
                f"    {name:<16} {_fmt_bytes(b):>12}  "
                f"{100.0 * b / total:5.1f}%"
            )
    if report.get("kv_capacity_bytes") is not None:
        head = report.get("kv_headroom_bytes")
        pct = report.get("kv_headroom_pct")
        line = (
            f"  kv pool capacity {_fmt_bytes(report['kv_capacity_bytes'])}"
        )
        if head is not None:
            line += f"   headroom {_fmt_bytes(head)}"
        if pct is not None:
            line += f" ({pct:.1f}%)"
        if report.get("kv_headroom_min_pct") is not None:
            line += f"   min {report['kv_headroom_min_pct']:.1f}%"
        lines.append(line)
    if report.get("host_held_bytes") is not None:
        # The host tier's own line (ISSUE 20) — mirrors the pool line
        # so "which tier is full" is readable at a glance.
        line = f"  host tier held {_fmt_bytes(report['host_held_bytes'])}"
        cap = report.get("host_capacity_bytes")
        if cap:
            line += (
                f" of {_fmt_bytes(cap)} "
                f"({100.0 * report['host_held_bytes'] / cap:.1f}%)"
            )
        if report.get("host_held_peak_bytes") is not None:
            line += f"   peak {_fmt_bytes(report['host_held_peak_bytes'])}"
        lines.append(line)
    rec = report.get("reconciliation")
    if rec:
        if rec.get("device_bytes") is not None:
            verdict = (
                "within tolerance" if rec.get("within_tolerance")
                else "OUT OF TOLERANCE"
            )
            lines.append(
                f"  device reconcile: ledger "
                f"{_fmt_bytes(rec['ledger_bytes'])} vs device "
                f"{_fmt_bytes(rec['device_bytes'])} "
                f"(delta {rec['delta_pct']}%) — {verdict}"
            )
        else:
            lines.append(
                f"  device reconcile: modeled only "
                f"(platform={rec.get('platform')}, no device bytes)"
            )
    ev = report.get("eviction_candidates")
    if ev:
        lines.append(f"  eviction candidates ({len(ev)}, coldest first):")
        for c in ev[:8]:
            # ``tier`` names where the candidate currently LIVES
            # (ISSUE 20): reclaiming an hbm candidate buys pool pages,
            # a host one buys host capacity at the price of a hit.
            tier = f" tier={c['tier']}" if c.get("tier") else ""
            lines.append(
                f"    {c.get('kind', '?'):<20} "
                f"{_fmt_bytes(c.get('bytes', 0)):>12}  "
                f"last_touch=t{c.get('last_touch_tick', 0)}{tier} "
                f"{c.get('rid', c.get('key', ''))}"
            )
    ex = report.get("exhaustion")
    if ex:
        pressure = (
            f" pressure={ex['tier_pressure']}"
            if ex.get("tier_pressure") else ""
        )
        lines.append(
            f"  last exhaustion: tick={ex.get('tick')} "
            f"headroom={_fmt_bytes(ex.get('kv_headroom_bytes', 0))}"
            + pressure
        )
        for h in ex.get("top_holders", [])[:5]:
            lines.append(
                f"    holder {str(h.get('rid', h.get('tenant', '?'))):<12} "
                f"{_fmt_bytes(h.get('bytes', 0)):>12}"
            )
    lines.append(
        "  conservation: "
        + ("ok (grants - frees == held)" if report["conservation_ok"]
           else "VIOLATED — instrumentation bug, bytes unattributed")
    )
    return "\n".join(lines)


def _fmt_bytes(b) -> str:
    b = float(b or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(b) < 1024.0 or unit == "GiB":
            return f"{b:.1f}{unit}" if unit != "B" else f"{int(b)}B"
        b /= 1024.0
    return f"{b:.1f}GiB"
