"""ImageNet ResNet-50 — baseline config #4: sync allreduce + sharded goo.

Beyond the reference (which stops at AlexNet; SURVEY.md §3.3): this config
exists to exercise exactly the north-star machinery — the synchronous
``psum`` gradient path with the goo optimizer state sharded across chips
(ZeRO-1). BatchNorm batch statistics ride the train step's ``stateful``
path and are pmean-synced across replicas each step.

SPMD-only: the async parity protocol has no story for BN state (the
reference never had BN), so ``--mode parity`` is rejected rather than
silently wrong.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from mpit_tpu.asyncsgd import runner
from mpit_tpu.asyncsgd.config import TrainConfig, from_argv
from mpit_tpu.data import synthetic_imagenet
from mpit_tpu.models import ResNet50


@dataclasses.dataclass
class ResnetConfig(TrainConfig):
    image_size: int = 224
    num_classes: int = 1000
    lr: float = 0.1
    weight_decay: float = 1e-4
    # BN implementation: "scale_shift" (models/norm.py, the round-5
    # default) or "flax" (nn.BatchNorm). The two are numerically
    # parity-tested but name their modules differently, so the
    # checkpoint tree differs — as a workload-config field this is
    # pinned by run_meta (ensure_meta), and pre-round-5 checkpoint
    # directories resume with --bn-impl flax.
    bn_impl: str = "scale_shift"


def main(argv: list[str] | None = None, **overrides) -> dict:
    cfg = from_argv(ResnetConfig, argv, prog="asyncsgd.resnet", overrides=overrides)
    if cfg.mode == "parity":
        raise SystemExit(
            "resnet50 is SPMD-only: the async parity protocol predates "
            "BatchNorm and has no defined semantics for its running stats"
        )
    print(runner.describe(cfg, "imagenet-resnet50"))
    dataset = runner.classification_dataset(
        cfg,
        lambda: synthetic_imagenet(
            image_size=cfg.image_size, num_classes=cfg.num_classes, seed=cfg.seed
        ),
    )
    if cfg.data_dir:
        cfg = dataclasses.replace(
            cfg,
            num_classes=dataset.num_classes,
            image_size=dataset.image_shape[0],
        )
    if cfg.bn_impl not in ("scale_shift", "flax"):
        raise SystemExit(f"--bn-impl must be scale_shift or flax, got {cfg.bn_impl!r}")
    if cfg.bn_impl == "flax":
        import flax.linen as nn

        model = ResNet50(num_classes=cfg.num_classes, norm=nn.BatchNorm)
    else:
        model = ResNet50(num_classes=cfg.num_classes)

    def init_params():
        variables = model.init(
            jax.random.key(cfg.seed),
            jnp.zeros((2, cfg.image_size, cfg.image_size, 3)),
        )
        return variables["params"], variables["batch_stats"]

    def loss_fn(params, batch_stats, batch):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"],
            mutable=["batch_stats"],
        )
        loss = runner.softmax_xent(logits, batch["label"])
        aux = {"accuracy": runner.accuracy(logits, batch["label"])}
        return loss, aux, mutated["batch_stats"]

    def eval_fn(params, batch_stats, batch):
        # Inference mode: BN normalizes with the pmean-synced running
        # averages (no mutable collection) — eval accuracy is a true
        # inference-mode number (round-1 advisor finding).
        logits = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"],
            train=False,
        )
        v = batch.get("valid")
        out = {
            "loss": runner.softmax_xent(logits, batch["label"], v),
            "top1": runner.accuracy(logits, batch["label"], v),
        }
        if cfg.num_classes > 5:
            out["top5"] = runner.topk_accuracy(logits, batch["label"], 5, v)
        if v is not None:
            out["_weight"] = jnp.sum(v)  # exact-count combine (runner.py)
        return out

    stream = runner.make_stream(cfg, dataset)
    return runner.run_spmd(
        cfg,
        stream,
        loss_fn,
        init_params,
        stateful=True,
        eval_fn=eval_fn,
        eval_batch=dataset.eval_batch(cfg.eval_batch),
        stream_factory=lambda skip: runner.make_stream(cfg, dataset, skip=skip),
        val_sweep=runner.make_val_sweep(cfg, dataset),
    )


if __name__ == "__main__":
    print(main())
