"""Serving entry point: ``python -m mpit_tpu.serve [options]``.

Loads a trained dense checkpoint (``--ckpt state.npz``, the
``train.convert --save-dense`` format) or random-inits a model
(``--model tiny|small``), serves a request stream through the
continuous-batching engine, and prints one JSON result: the serving
stats (tokens/s, TTFT and latency percentiles, occupancy) plus the obs
phase summary. ``--mesh model=2`` selects the tensor-parallel engine.

Two drive modes (ISSUE 6):

- default — the closed-loop synthetic stream: ``--requests N`` all
  submitted up front, run to drain;
- ``--loadgen "rate=8,process=bursty,tenants=4"`` — the OPEN-loop
  production harness: a seeded ``serve.loadgen`` arrival trace driven
  by its own clock through ``Server.run_timed`` for ``--duration``
  seconds, with a live windowed stats line on stderr every
  ``--stats-interval`` seconds (rolling p50/p95 TTFT and latency,
  req/s, tokens/s, occupancy, queue depth) fed from the
  ``obs.stream`` registry — not from the Recorder's bounded buffer.

``--kv-pages N`` (ISSUE 7) selects the PAGED engine: a fixed pool of
``--kv-page-size``-token pages shared by all slots (HBM scales with
tokens actually held, not slots × max-len), copy-on-write prefix
sharing keyed on prompt prefixes (drive it with ``--loadgen
"...,prefix=32"``), and ``--prefill-chunk`` slicing long admits across
decode ticks; the live stats line grows ``kv=`` (pool occupancy),
``kvtok=`` (tokens cached) and ``shr=`` (pages stored once, mapped by
several requests).

``--kv-dtype int8`` (ISSUE 15) quantizes the KV cache: int8 rows +
per-(row, head) scale blocks in HBM, dequantized per visited tile
inside the decode kernel — the dominant decode HBM sweep shrinks ~2×
vs bf16 and the same pool budget holds ~2× the tokens. The stats line
shows the wire dtype (``kvd=``); ``--kv-dtype f32|bf16`` simply pin
the dense cache dtype. Rejected with ``--decode-attention reference``
(the oracle path dequantizes the whole cache per tick).

``--weights-dtype int8`` (ISSUE 17) quantizes the OTHER ~92% of the
decode sweep: every matmul weight (qkv/proj/fc/out kernels, wte, the
head) stored as int8 + per-row f32 scales, dequantized one block at a
time inside the blocked matmuls — never a full f32 weight in HBM. The
stats line shows ``wd=``; composes freely with ``--kv-dtype int8``
(together they quantize essentially the whole decode sweep). Rejected
with ``--decode-attention reference`` for the same reason as the KV
flag: the reference path materializes whole dequantized weights (the
parity oracle, not a serving path).

Roofline flight data (ISSUE 8): the engine's jitted steps register
their ``cost_analysis()`` costs at warm, every decode tick feeds the
length-aware achieved HBM bytes (visited-tile model) into the recorder
and the rolling windows, the live stats line gains ``hbmbw=`` (windowed
achieved GB/s) and ``mfu=`` (on-TPU only — off-chip it reads ``-``,
never a fabricated percentage), and the final JSON carries the
per-phase ``roofline`` roll-up plus ``engine_compiles`` (pinned
lifetime compile count; an unexpected recompile lands in the sentinel).

``--slo-ttft-p95 / --slo-latency-p95 / --slo-shed-rate`` declare SLO
targets; an ``obs.slo.SLOMonitor`` evaluates them over the rolling
windows each tick, breaches land in the trace / the sentinel, and the
final JSON carries the monitor's report (time in breach, time to
detect). ``--max-queue`` bounds intake (excess arrivals shed).

``--policy`` (ISSUE 12) swaps the FIFO scheduler for the scheduling-
policy tier (``serve.policy``): ``--policy on`` takes the defaults, or
a spec like ``"quantum=4,preempt=1,admission_factor=1.2,weight.t0=2"``.
Priority classes and per-class TTFT targets ride the load spec
(``--loadgen "...,priority=1,ttft_target=0.2"`` stamps every class; the
programmatic mixture sets them per class). The live stats line grows
``pre=`` (preemptions) under a policy, and the final JSON carries the
policy block (preemptions, resumes, admission sheds, tier depths) plus
the per-tenant roll-up and cause-split shed counts from
``Server.stats()``.

Config follows the ``asyncsgd.config`` pattern: one dataclass, argparse
generated from its fields.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

import numpy as np

from mpit_tpu.asyncsgd.config import from_argv


@dataclasses.dataclass
class ServeConfig:
    """Options for the serving CLI (the ``opt`` table analogue)."""

    ckpt: str = ""  # dense .npz from --save-dense ("" = random init)
    model: str = "tiny"  # random-init size: tiny | small
    num_heads: int = 0  # ckpt head-count override (0 = d_model//64)
    slots: int = 4  # concurrent KV-cache slots
    max_len: int = 96  # per-slot cache length (prompt + generation)
    prefill_len: int = 32  # padded prompt buffer width
    requests: int = 16  # synthetic stream size
    prompt_len: int = 8  # max synthetic prompt length (uniform 1..N)
    max_new_tokens: int = 16
    temperature: float = 0.0  # <=0 greedy
    top_k: int = 0  # 0 = full vocab
    # Serving hot-loop implementation (ISSUE 5): kernel = Pallas
    # flash-decode + blocked LM-head sampling (reference fallback off
    # TPU); reference = the dense PR 4 path; interpret = force the
    # kernel through the Pallas interpreter (CPU testing).
    decode_attention: str = "kernel"
    # Blocked sampler's candidate-buffer width — bounds --top-k under
    # kernel/interpret modes (submit rejects top_k > this). Grown here
    # so the remedy the rejection names is reachable from the CLI.
    sample_k_cap: int = 128
    # Paged KV cache (ISSUE 7). kv_pages > 0 selects the paged engine:
    # HBM holds kv_pages × kv_page_size cache rows shared by all slots
    # (max_len becomes a per-slot VIRTUAL capacity), prompts sharing a
    # prefix map the same pages copy-on-write, and prefill_chunk > 0
    # slices long admits across ticks so they can't head-of-line-block
    # decode (0 = whole-prompt chunks).
    kv_pages: int = 0
    kv_page_size: int = 16
    prefill_chunk: int = 0
    # Host KV tier (ISSUE 20). kv_host_pages > 0 gives the paged
    # engine a host-RAM page store: preemption victims park their
    # pages there (resume restreams instead of re-prefilling) and
    # dying sole-reader prefix entries migrate there (admission hits
    # keep working after their HBM pages are reclaimed). Passed
    # through unconditionally so --kv-host-pages without --kv-pages
    # surfaces the Engine's "paged-engine knob" rejection.
    kv_host_pages: int = 0
    # KV cache wire dtype (ISSUE 15). "" = the model dtype (default
    # path, byte-identical); f32|bf16 pin the cache dtype; int8 stores
    # quantized rows + per-(row, head) scales and fuses the dequant
    # into the decode kernel's per-tile DMA loop — ~2x fewer decode
    # HBM bytes than bf16, ~2x tokens at the same pool budget.
    # Rejected with --decode-attention reference: the dense reference
    # path dequantizes the WHOLE cache per tick (it exists as the
    # parity oracle, not a serving path — the perf the flag buys needs
    # the fused per-tile dequant of kernel/interpret).
    kv_dtype: str = ""
    # Weight store wire dtype (ISSUE 17). "" = dense params as loaded
    # (default path, byte-identical); "int8" quantizes every matmul
    # weight (per-row int8 + f32 scale through the shared rounding
    # contract) and runs the blocked fused-dequant matmuls — the param
    # term of the decode HBM sweep shrinks ~4x, with the same engine
    # step surface and compile pins. Rejected with --decode-attention
    # reference (the whole-dequant parity oracle, not a serving path).
    weights_dtype: str = ""
    # Speculative decoding (ISSUE 13). spec_k > 0 swaps the decode tick
    # for draft-then-verify (k drafted tokens per slot, one T=k+1 target
    # verify, longest-prefix acceptance with cache rollback). The draft
    # comes from --draft-ckpt (a dense .npz, any tier's export) or
    # --draft-config ("tiny" = random-init tiny config at the target's
    # vocab; "truncate:N" = the target's own first N blocks — the
    # self-speculation draft, no second checkpoint needed).
    spec_k: int = 0
    draft_ckpt: str = ""
    draft_config: str = ""
    draft_num_heads: int = 0  # --draft-ckpt head-count override
    mesh: str = ""  # e.g. "model=2" -> TP engine over that axis
    sentinel: bool = False  # decode/prefill tick anomaly sentinel
    trace: str = ""  # write a Chrome trace of the run here
    seed: int = 0
    # Open-loop load harness (ISSUE 6). loadgen = "" keeps the
    # closed-loop synthetic stream; otherwise a serve.loadgen spec
    # ("rate=8,process=poisson|bursty,on_fraction=0.25,tenants=4,
    # prompt_min=..,prompt_max=..,new_min=..,new_max=..").
    loadgen: str = ""
    duration: float = 10.0  # loadgen admission window, seconds
    drain: bool = True  # keep ticking past the window until drained
    max_queue: int = 0  # shed arrivals beyond this queue depth (0 = inf)
    window_s: float = 5.0  # rolling-window span for live stats / SLOs
    stats_interval: float = 2.0  # live stats line cadence (0 = silent)
    # SLO targets (0 = not declared). Evaluated over the rolling
    # windows; breaches emit slo_breach instants + sentinel notes.
    slo_ttft_p95: float = 0.0
    slo_latency_p95: float = 0.0
    slo_shed_rate: float = 0.0
    # Scheduling policy (ISSUE 12). "" = FIFO; "on" = defaults; or a
    # serve.policy spec: "quantum=4,preempt=1,admission_factor=1.2,
    # weight.<tenant>=2". Pair with --loadgen priority=/ttft_target=.
    policy: str = ""
    # Disaggregated fleet (ISSUE 19). "" = single-process server;
    # otherwise a serve.fleet spec ("prefill=2,decode=2[,lease_s=0.5,
    # heartbeat_s=0.05,admission_ttft_s=0.3]"): a router + prefill
    # workers shipping KV pages to decode workers over the compat
    # layer, driven by the closed-loop synthetic stream. Worker count
    # excludes the router; every worker builds its own engine from
    # THIS config's geometry flags.
    fleet: str = ""

    def mesh_shape(self) -> dict[str, int] | None:
        from mpit_tpu.asyncsgd.config import parse_mesh

        return parse_mesh(self.mesh)


def _build_engine(cfg: ServeConfig):
    import jax
    import jax.numpy as jnp

    import mpit_tpu
    from mpit_tpu.models import GPT2, GPT2Config
    from mpit_tpu.serve import Engine, load_gpt2_params

    world, tp_axis = None, None
    shape = cfg.mesh_shape()
    if shape:
        world = mpit_tpu.init(shape, set_default=False)
        tp_axis = "model" if "model" in shape else next(iter(shape))

    # Pure-flag rejections FIRST — before the checkpoint load / random
    # init pays a compile a doomed invocation never needed.
    if cfg.kv_dtype and cfg.kv_dtype not in ("f32", "bf16", "int8"):
        raise SystemExit(
            f"--kv-dtype {cfg.kv_dtype!r}: expected f32, bf16 or int8"
        )
    if cfg.kv_dtype == "int8" and cfg.decode_attention == "reference":
        # Precise submit-time rejection (ISSUE 15 satellite): the dense
        # reference engine HAS the dequant hooks (it is the parity
        # oracle) but dequantizes the whole cache every tick — serving
        # int8 through it pays quantization error for MORE bytes moved,
        # the opposite of what the flag promises.
        raise SystemExit(
            "--kv-dtype int8 with --decode-attention reference: the "
            "reference path materializes the full dequantized cache "
            "per tick (it is the parity oracle, not a serving path); "
            "use --decode-attention kernel (or interpret) for the "
            "fused per-tile dequant"
        )
    if cfg.weights_dtype and cfg.weights_dtype not in ("f32", "int8"):
        raise SystemExit(
            f"--weights-dtype {cfg.weights_dtype!r}: expected f32 or int8"
        )
    if cfg.weights_dtype == "int8" and cfg.decode_attention == "reference":
        # Same rule as --kv-dtype (ISSUE 17): the reference engine runs
        # the whole-dequant matmul oracle — quantization error for MORE
        # bytes moved, the opposite of the flag's promise.
        raise SystemExit(
            "--weights-dtype int8 with --decode-attention reference: "
            "the reference path materializes whole dequantized weights "
            "(it is the parity oracle, not a serving path); use "
            "--decode-attention kernel (or interpret) for the blocked "
            "fused-dequant matmuls"
        )

    if cfg.ckpt:
        params, mcfg = load_gpt2_params(cfg.ckpt, num_heads=cfg.num_heads)
    else:
        mcfg = (
            GPT2Config.small()
            if cfg.model == "small"
            else GPT2Config.tiny(max_seq_len=max(cfg.max_len, 128))
        )
        params = jax.jit(GPT2(mcfg).init)(
            jax.random.key(cfg.seed), jnp.zeros((1, 8), jnp.int32)
        )["params"]
    # Speculative-decode draft resolution + submit-time validation of
    # incompatible combinations (ISSUE 13 satellite): every rejection
    # here is a precise SystemExit BEFORE the first jitted step — never
    # a shape error (or silent corruption) inside one.
    draft_params, draft_cfg = None, None
    if cfg.spec_k:
        from mpit_tpu.serve import draft_from_target

        if cfg.draft_ckpt and cfg.draft_config:
            raise SystemExit(
                "--draft-ckpt and --draft-config are mutually "
                "exclusive: one draft model per engine"
            )
        if cfg.draft_ckpt:
            draft_params, draft_cfg = load_gpt2_params(
                cfg.draft_ckpt, num_heads=cfg.draft_num_heads
            )
        elif cfg.draft_config.startswith("truncate:"):
            try:
                n = int(cfg.draft_config.split(":", 1)[1])
            except ValueError:
                raise SystemExit(
                    f"--draft-config {cfg.draft_config!r}: expected "
                    "truncate:<num_layers>"
                )
            if not 1 <= n < mcfg.num_layers:
                raise SystemExit(
                    f"--draft-config truncate:{n}: need 1 <= N < the "
                    f"target's {mcfg.num_layers} layers (an equal-depth "
                    "draft costs what the target costs)"
                )
            draft_params, draft_cfg = draft_from_target(params, mcfg, n)
        elif cfg.draft_config == "tiny":
            draft_cfg = GPT2Config.tiny(
                vocab_size=mcfg.vocab_size,
                max_seq_len=mcfg.max_seq_len,
                dtype=mcfg.dtype,
            )
            draft_params = jax.jit(GPT2(draft_cfg).init)(
                jax.random.key(cfg.seed + 1), jnp.zeros((1, 8), jnp.int32)
            )["params"]
        else:
            raise SystemExit(
                f"--spec-k {cfg.spec_k} needs a draft: --draft-ckpt "
                f"state.npz, --draft-config tiny, or --draft-config "
                f"truncate:N (got draft_config={cfg.draft_config!r})"
            )
        if not cfg.kv_pages:
            # The dense verify needs spec_k-1 rows of headroom past
            # prompt + max_new (dynamic_update_slice clamps, it does
            # not drop); reject the geometry here, not per request.
            need = cfg.prompt_len + cfg.max_new_tokens + cfg.spec_k - 1
            if not cfg.loadgen and need > cfg.max_len:
                raise SystemExit(
                    f"--spec-k {cfg.spec_k}: prompt_len + max_new_tokens"
                    f" + spec_k - 1 = {need} > --max-len {cfg.max_len} "
                    "on the dense engine; shrink the stream, lower "
                    "--spec-k, grow --max-len, or use --kv-pages "
                    "(the paged engine drops out-of-range draft rows)"
                )
    elif cfg.draft_ckpt or cfg.draft_config:
        raise SystemExit(
            "--draft-ckpt/--draft-config require --spec-k >= 1"
        )
    engine = Engine(
        mcfg,
        params,
        slots=cfg.slots,
        max_len=cfg.max_len,
        prefill_len=cfg.prefill_len,
        world=world,
        tp_axis=tp_axis,
        seed=cfg.seed,
        decode_attention=cfg.decode_attention,
        sample_k_cap=max(cfg.sample_k_cap, cfg.top_k),
        kv_pages=cfg.kv_pages or None,
        kv_page_size=cfg.kv_page_size,
        kv_host_pages=cfg.kv_host_pages or None,
        # Passed through unconditionally: --prefill-chunk without
        # --kv-pages must surface the Engine's "paged-engine knob"
        # rejection, not silently run whole-prompt prefills.
        prefill_chunk=cfg.prefill_chunk or None,
        spec_k=cfg.spec_k,
        draft_params=draft_params,
        draft_cfg=draft_cfg,
        kv_dtype=cfg.kv_dtype or None,
        weights_dtype=cfg.weights_dtype or None,
    )
    return engine, mcfg


def synthetic_requests(cfg: ServeConfig, vocab_size: int):
    """A reproducible request stream: uniform prompt lengths 1..N,
    uniform token ids, the CLI's sampling settings."""
    from mpit_tpu.serve import Request

    rng = np.random.RandomState(cfg.seed)
    for i in range(cfg.requests):
        plen = int(rng.randint(1, cfg.prompt_len + 1))
        yield Request(
            rid=i,
            prompt=rng.randint(0, vocab_size, size=plen).tolist(),
            max_new_tokens=cfg.max_new_tokens,
            temperature=cfg.temperature,
            top_k=cfg.top_k,
        )


def _slo_targets(cfg: ServeConfig):
    from mpit_tpu.obs.slo import SLO

    targets = []
    if cfg.slo_ttft_p95 > 0:
        targets.append(SLO.ttft_p95(cfg.slo_ttft_p95))
    if cfg.slo_latency_p95 > 0:
        targets.append(SLO.latency_p95(cfg.slo_latency_p95))
    if cfg.slo_shed_rate > 0:
        targets.append(SLO.shed_rate(cfg.slo_shed_rate))
    return targets


def _live_line(registry, monitor, server, now: float) -> str:
    """One windowed stats line — everything on it comes from the
    rolling windows (O(buckets)), never from the Recorder's buffer."""
    ws = registry.window_stats()
    h, r, g = ws["histograms"], ws["rates"], ws["gauges"]

    def ms(name, k):
        v = h.get(name, {}).get(k)
        return f"{v * 1000:.0f}" if v is not None else "-"

    line = (
        f"[t={now:6.1f}s] "
        f"ttft p50/p95={ms('request_ttft', 'p50')}/"
        f"{ms('request_ttft', 'p95')}ms "
        f"lat p95={ms('request_latency', 'p95')}ms "
        f"req/s={r.get('serve_arrivals', {}).get('rate_per_s', 0.0):.1f} "
        f"tok/s={r.get('serve_tokens', {}).get('rate_per_s', 0.0):.0f} "
        f"occ={g.get('slot_occupancy', 0.0):.2f} "
        f"q={g.get('queue_depth', 0.0):.0f} "
        f"done={len(server.completed)} shed={len(server.shed)}"
    )
    if server.policy is not None:
        line += f" pre={server.policy.preemptions}"
    if getattr(server.engine, "kv_dtype_explicit", False):
        # The cache WIRE dtype (ISSUE 15): what the decode sweep
        # actually moves — shown whenever it was explicitly chosen, so
        # an int8 run's hbmbw= figure is attributable from the line.
        line += f" kvd={server.engine.kv_dtype}"
    if getattr(server.engine, "weights_dtype_explicit", False):
        # The weight store's wire dtype (ISSUE 17): the param term of
        # the same sweep.
        line += f" wd={server.engine.weights_dtype}"
    if "kv_pool_occupancy" in g:
        # Cache-MEMORY efficiency next to slot occupancy (ISSUE 7):
        # pool fill, tokens actually held, pages stored once but
        # mapped by multiple requests.
        line += (
            f" kv={g['kv_pool_occupancy']:.2f}"
            f" kvtok={g.get('kv_tokens_cached', 0.0):.0f}"
            f" shr={g.get('prefix_pages_shared', 0.0):.0f}"
        )
    if "hbm_held_bytes" in g:
        # Byte-exact memory view (ISSUE 18): total ledger-held HBM,
        # the KV pool's held share, and the admission headroom — the
        # same numbers a refused admit is annotated with.
        line += (
            f" hbm={g['hbm_held_bytes'] / 1e6:.1f}MB"
            f" held={g.get('kv_held_bytes', 0.0) / 1e6:.1f}MB"
            f" headroom={g.get('kv_headroom_pct', 0.0):.0f}%"
        )
    bw = r.get("decode_hbm_bytes", {}).get("rate_per_s", 0.0)
    if bw:
        # Windowed utilization (ISSUE 8): the length-aware decode HBM
        # rate from the rolling window (visited-tile bytes, not the
        # padded model). MFU only when the platform IS the chip —
        # off-TPU the flops rate against a TPU peak would be fiction,
        # so the field shows "-" and the final JSON carries the
        # platform-labeled roofline block instead.
        line += f" hbmbw={bw / 1e9:.2f}GB/s"
        fl = r.get("decode_flops", {}).get("rate_per_s", 0.0)
        if fl and getattr(server.engine, "platform", "") == "tpu":
            from mpit_tpu.obs.roofline import chip_peaks

            line += f" mfu={100.0 * fl / chip_peaks()['peak_flops']:.1f}%"
        else:
            line += " mfu=-"
    if monitor is not None:
        breached = [
            name
            for name, t in monitor.report()["targets"].items()
            if t["in_breach"]
        ]
        if breached:
            line += " SLO-BREACH:" + ",".join(breached)
    return line


def _run_fleet_cli(cfg: ServeConfig) -> dict:
    """``--fleet prefill=P,decode=D``: the disaggregated serving fleet
    over the closed-loop synthetic stream. One JSON result: completion
    counts, per-worker roll-ups, fleet req/s, and the flight block's
    P2P matrix (KV shipment bytes visible per (src, dst))."""
    from mpit_tpu.serve.fleet import parse_fleet_spec, run_fleet

    fcfg = parse_fleet_spec(cfg.fleet)
    engine0, mcfg = _build_engine(cfg)
    seed_engines = [engine0]

    def factory(role, rank):
        # Same config + same seed → identical params on every worker
        # (the bit-match precondition); the probe engine built for the
        # vocab lookup serves the first worker instead of leaking.
        if seed_engines:
            return seed_engines.pop()
        engine, _ = _build_engine(cfg)
        return engine

    requests = list(synthetic_requests(cfg, mcfg.vocab_size))
    t0 = time.perf_counter()
    out = run_fleet(
        factory,
        requests,
        prefill=fcfg.prefill,
        decode=fcfg.decode,
        heartbeat_s=fcfg.heartbeat_s,
        lease_s=fcfg.lease_s,
        admission_ttft_s=fcfg.admission_ttft_s,
        job_timeout_s=fcfg.job_timeout_s,
    )
    wall = time.perf_counter() - t0
    completed = out["completed"]
    result = {
        "model": {
            "layers": mcfg.num_layers,
            "d_model": mcfg.d_model,
            "vocab": mcfg.vocab_size,
            "source": cfg.ckpt or f"random-init {cfg.model}",
        },
        "fleet": {"prefill": fcfg.prefill, "decode": fcfg.decode},
        "wall_s": round(wall, 4),
        "requests_completed": len(completed),
        "requests_shed": len(out["shed"]),
        "fleet_req_per_s": round(len(completed) / wall, 2) if wall else None,
        "generated_tokens": sum(len(t) for t in completed.values()),
        "router": {
            k: v
            for k, v in out["router"].items()
            if k not in ("completed", "role")
        },
        "workers": out["workers"],
    }
    flight = out.get("flight")
    if flight is not None:
        result["p2p_bytes"] = np.asarray(flight["p2p_bytes"]).tolist()
    return result


def main(argv: list[str] | None = None) -> dict:
    cfg = from_argv(ServeConfig, argv, prog="python -m mpit_tpu.serve")
    if cfg.fleet:
        return _run_fleet_cli(cfg)
    from mpit_tpu import obs
    from mpit_tpu.obs.slo import SLOMonitor
    from mpit_tpu.obs.stream import StreamRegistry
    from mpit_tpu.serve import (
        SchedulingPolicy,
        Server,
        generate_arrivals,
        parse_load_spec,
        parse_policy_spec,
        warm_engine,
    )

    rec = obs.enable(obs.Recorder())
    sentinel = (
        obs.Sentinel(phases=("decode", "prefill"), warmup=4)
        if cfg.sentinel
        else None
    )
    engine, mcfg = _build_engine(cfg)
    registry = StreamRegistry(window_s=cfg.window_s)
    targets = _slo_targets(cfg)
    monitor = (
        SLOMonitor(targets, registry, sentinel=sentinel) if targets else None
    )
    policy = (
        SchedulingPolicy(parse_policy_spec(cfg.policy), registry)
        if cfg.policy
        else None
    )
    spec = parse_load_spec(cfg.loadgen) if cfg.loadgen else None
    if spec is not None:
        # Fail BEFORE the timed window, not on whichever arrival first
        # draws a long prompt mid-trace: submit() treats an oversized
        # request as a caller bug, and for the CLI the caller is the
        # spec/geometry pair given right here.
        for klass in spec.classes:
            if klass.max_prompt_total > cfg.prefill_len:
                raise SystemExit(
                    f"--loadgen class {klass.name!r}: prefix + prompt_max "
                    f"{klass.max_prompt_total} > --prefill-len "
                    f"{cfg.prefill_len}"
                )
            need = klass.max_prompt_total + klass.max_new_tokens[1]
            if need > cfg.max_len:
                raise SystemExit(
                    f"--loadgen class {klass.name!r}: prefix + prompt_max "
                    f"+ new_max = {need} > --max-len {cfg.max_len}"
                )
            if cfg.spec_k and not cfg.kv_pages and (
                need + cfg.spec_k - 1 > cfg.max_len
            ):
                raise SystemExit(
                    f"--loadgen class {klass.name!r} + --spec-k "
                    f"{cfg.spec_k}: the dense verify needs spec_k-1 "
                    f"rows of headroom — prefix + prompt_max + new_max "
                    f"+ spec_k - 1 = {need + cfg.spec_k - 1} > "
                    f"--max-len {cfg.max_len}; lower --spec-k, grow "
                    "--max-len, or use --kv-pages"
                )
        # Warm the engine's two compiles OUTSIDE the timed window — an
        # open-loop harness that pays multi-second XLA compiles inside
        # its first arrivals' TTFT measures the compiler, not the
        # server. register_costs: the steps' cost_analysis lands in the
        # recorder so the final JSON (and the live mfu=/hbmbw= fields)
        # carry the roofline view (ISSUE 8).
        warm_engine(engine, register_costs=True)
        arrivals = generate_arrivals(
            spec,
            vocab_size=mcfg.vocab_size,
            duration_s=cfg.duration,
            seed=cfg.seed,
        )
        server = Server(
            engine,
            sentinel=sentinel,
            stream=registry,
            slo=monitor,
            max_queue=cfg.max_queue or None,
            policy=policy,
        )
        last_line = [0.0]

        def on_tick(srv, now):
            if cfg.stats_interval <= 0:
                return
            if now - last_line[0] < cfg.stats_interval:
                return
            last_line[0] = now
            print(
                _live_line(registry, monitor, srv, now),
                file=sys.stderr,
                flush=True,
            )

        t0 = time.perf_counter()
        server.run_timed(
            arrivals,
            duration=cfg.duration,
            drain=cfg.drain,
            on_tick=on_tick,
        )
        wall = time.perf_counter() - t0
    else:
        server = Server(
            engine,
            sentinel=sentinel,
            stream=registry,
            slo=monitor,
            max_queue=cfg.max_queue or None,
            policy=policy,
        )
        for req in synthetic_requests(cfg, mcfg.vocab_size):
            server.submit(req)
        t0 = time.perf_counter()
        server.run()
        wall = time.perf_counter() - t0

    if getattr(engine, "roofline_costs", None) is None:
        # Closed-loop path (no warm): register the step costs now —
        # registration is time-independent, so doing it after the run
        # still yields the full roofline roll-up below.
        try:
            engine.register_roofline()
        except Exception:
            pass  # backends without AOT cost support: phases-only output
    summ = rec.summary()
    stats = server.stats()
    decode_s = summ["phases"].get("decode", {}).get("total_s", 0.0)
    gen = stats["generated_tokens"]
    # First tokens come from prefill; decode throughput counts the rest.
    decode_tokens = gen - stats["requests_completed"]
    out = {
        "model": {
            "layers": mcfg.num_layers,
            "d_model": mcfg.d_model,
            "vocab": mcfg.vocab_size,
            "source": cfg.ckpt or f"random-init {cfg.model}",
        },
        "wall_s": round(wall, 4),
        "decode_tokens_per_sec": (
            round(decode_tokens / decode_s, 2) if decode_s else None
        ),
        "decode_attention": engine.decode_attention_mode,
        "decode_sampler": engine.decode_sampler,
        **stats,
        "obs_summary": {
            name: {
                k: round(v, 6) if isinstance(v, float) else v
                for k, v in p.items()
            }
            for name, p in summ["phases"].items()
        },
    }
    if summ.get("roofline"):
        # Per-phase measured-vs-modeled utilization (ISSUE 8):
        # platform-labeled; percentage verdicts only on the real chip.
        out["roofline"] = summ["roofline"]
    if spec is not None:
        out["load"] = {
            "rate": spec.rate,
            "process": spec.process,
            "tenants": spec.tenants,
            "duration_s": cfg.duration,
            "arrivals": len(arrivals),
            "shed": len(server.shed),
        }
        out["window_stats"] = registry.window_stats()
    if monitor is not None:
        out["slo"] = monitor.report()
    if sentinel is not None:
        out["sentinel"] = sentinel.report()
    if cfg.trace:
        obs.export_chrome_trace(cfg.trace, recorder=rec)
        out["trace"] = cfg.trace
    obs.disable()
    return out


if __name__ == "__main__":
    print(json.dumps(main(sys.argv[1:])))
