"""SLO-aware scheduling policy: priority, fairness, admission, preemption.

The continuous-batching scheduler (``serve.scheduler``) was FIFO: one
queue, drained in arrival order, with ``max_queue`` as the only control
under load. Nothing *decided* anything — "max sustained req/s at p95
TTFT ≤ target" was measured against the dumbest possible policy (ISSUE
12 motivation; ROADMAP item 4). This module is the decision layer the
``Server`` consults at every admit/decode boundary, replaying the
reference's pserver arc — a request loop arbitrating concurrent clients
— at production serving scale, where arbitration means priority,
fairness and admission instead of tag matching:

- **Priority tiers** — requests carry a ``priority`` class (0 =
  highest / interactive); the admit loop drains queues in strict tier
  order instead of one FIFO. A lower tier runs only when every higher
  tier is empty (sustained high-tier overload CAN starve lower tiers —
  that is the declared contract; admission shedding is the relief
  valve, not tier mixing).
- **Per-tenant fairness** — deficit-weighted round-robin WITHIN a tier:
  each tenant queue earns ``quantum × weight`` credits when the
  rotation reaches it and spends one per admitted request, so one
  tenant's burst cannot starve the others beyond its weight share.
  Invariant (test-pinned): deficit counters stay bounded —
  ``deficit ≤ max(quantum × weight, 1)`` always (+1 transiently after
  a failed-admission refund), and a tenant whose queue empties forfeits
  its balance (the classic DRR no-banking rule).
- **SLO-aware admission** — a projected-TTFT estimator
  (:class:`TTFTProjector`: queue depth × measured prefill-tick cost +
  current decode-tick cost, read from the stream registry's rolling
  windows) decides shed-vs-queue at submit: when the projection already
  breaches the request's TTFT target, queueing it would only manufacture
  a guaranteed SLO miss — shed it NOW (``shed_admission``, distinct from
  ``shed_queue_full`` bounded intake). Cold windows abstain: admission
  shedding needs evidence, not priors.
- **Preemption** — when the best queued tier's longest-waiting request
  is projected to miss its TTFT target and no capacity frees, the
  server evicts a LOWER-tier live generation: its pages go back to the
  :class:`~mpit_tpu.serve.kvcache.PageAllocator`, the request is parked
  host-side with its generated-so-far tokens, and it re-enters its own
  tier's queue at the FRONT to resume later through the existing
  chunked-prefill path (feed = prompt + generated tokens — the prefix
  index makes the re-prefill cheap when the prefix is still cached).
  Pinned invariant: a preempted-then-resumed greedy request bit-matches
  its un-preempted output (the resume prefill computes exactly the
  decode tick it displaced — same cache rows, same logits row).
  Paged engines only (a dense slot has no pages to free);
  ``max_preemptions`` bounds thrash per request.

The policy is pure host bookkeeping — no device state, no jax. The
``Server`` owns WHEN to consult it (submit → :meth:`should_shed`,
admit → :meth:`next`/:meth:`restore`, capacity miss →
:meth:`wants_preemption`/:meth:`pick_victim`); the policy owns the
ordering/verdict logic, so a different policy is a different class, not
a different scheduler.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Mapping

__all__ = [
    "PolicyConfig",
    "SchedulingPolicy",
    "TTFTProjector",
    "parse_policy_spec",
]


@dataclasses.dataclass(frozen=True)
class PolicyConfig:
    """Knobs for one :class:`SchedulingPolicy`.

    ``quantum``: DRR credits granted per rotation visit (requests-worth;
    a tenant with weight ``w`` can admit up to ``max(quantum × w, 1)``
    requests per turn before the rotation moves on). ``tenant_weights``
    maps tenant id → weight (missing tenants get 1.0). ``admission``
    enables projected-TTFT shedding; a request is shed when the
    projection exceeds ``admission_factor ×`` its TTFT target.
    ``preempt`` enables eviction of lower-tier live generations (paged
    engines only); one request is preempted at most
    ``max_preemptions`` times. ``projection_quantile``/``min_samples``
    shape the estimator (see :class:`TTFTProjector`).
    """

    quantum: float = 4.0
    tenant_weights: Mapping[str, float] = dataclasses.field(
        default_factory=dict
    )
    admission: bool = True
    admission_factor: float = 1.0
    preempt: bool = True
    max_preemptions: int = 3
    projection_quantile: float = 0.5
    min_samples: int = 4

    def __post_init__(self):
        if self.quantum <= 0:
            raise ValueError(f"quantum must be > 0, got {self.quantum}")
        for t, w in self.tenant_weights.items():
            if w <= 0:
                raise ValueError(
                    f"tenant {t!r}: weight must be > 0, got {w}"
                )
        if self.admission_factor <= 0:
            raise ValueError(
                f"admission_factor must be > 0, got {self.admission_factor}"
            )
        if self.max_preemptions < 0:
            raise ValueError(
                f"max_preemptions must be >= 0, got {self.max_preemptions}"
            )
        if not 0.0 < self.projection_quantile <= 1.0:
            raise ValueError(
                f"projection_quantile must be in (0, 1], got "
                f"{self.projection_quantile}"
            )
        if self.min_samples < 1:
            raise ValueError(
                f"min_samples must be >= 1, got {self.min_samples}"
            )


_BOOL_KEYS = ("admission", "preempt")
_FLOAT_KEYS = ("quantum", "admission_factor", "projection_quantile")
_INT_KEYS = ("max_preemptions", "min_samples")


def parse_policy_spec(text: str) -> PolicyConfig:
    """``"quantum=4,preempt=1,admission_factor=1.2,weight.t0=2"`` →
    :class:`PolicyConfig` (the serve CLI's ``--policy`` value; the
    literals ``on`` / ``default`` select the defaults)."""
    text = text.strip()
    if text in ("on", "default", "1", "true"):
        return PolicyConfig()
    kw: dict[str, Any] = {}
    weights: dict[str, float] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"--policy parts are key=value, got {part!r}")
        key, val = part.split("=", 1)
        key = key.strip()
        if key.startswith("weight."):
            weights[key[len("weight."):]] = float(val)
        elif key in _BOOL_KEYS:
            kw[key] = val.strip().lower() in ("1", "true", "yes", "on")
        elif key in _FLOAT_KEYS:
            kw[key] = float(val)
        elif key in _INT_KEYS:
            kw[key] = int(val)
        else:
            raise ValueError(
                f"unknown --policy key {key!r} (valid: "
                f"{', '.join((*_FLOAT_KEYS, *_INT_KEYS, *_BOOL_KEYS))}, "
                f"weight.<tenant>)"
            )
    if weights:
        kw["tenant_weights"] = weights
    return PolicyConfig(**kw)


class TTFTProjector:
    """Projected TTFT for a request entering the queue NOW.

    The model (ISSUE 12): the queue ahead drains roughly one request
    per prefill tick, so a request behind ``depth`` others waits
    ``depth`` prefill ticks, pays its own, and sits behind the decode
    tick in flight::

        projected = (depth + 1) × prefill_tick + decode_tick

    Both tick costs come from the stream registry's rolling windows
    (``prefill_tick`` / ``decode_tick`` series, fed by the Server once
    per tick) at ``quantile`` (default p50 — the projection is a
    central estimate, not a tail bound; ``admission_factor`` is where
    callers buy slack). Fewer than ``min_samples`` windowed prefill
    observations → ``None`` (abstain): a cold server must not shed on
    a guess.
    """

    def __init__(self, registry, *, quantile: float = 0.5,
                 min_samples: int = 4):
        self.registry = registry
        self.quantile = quantile
        self.min_samples = min_samples

    def projected_ttft_s(self, queue_depth: int) -> float | None:
        reg = self.registry
        if reg is None:
            return None
        if reg.window_count("prefill_tick") < self.min_samples:
            return None
        pf = reg.quantile("prefill_tick", self.quantile)
        if pf is None:
            return None
        dc = reg.quantile("decode_tick", self.quantile) or 0.0
        return (queue_depth + 1) * pf + dc


class _TierState:
    """One priority tier's DRR machinery: per-tenant FIFO deques, a
    rotation ring, and the deficit counters."""

    __slots__ = ("queues", "ring", "deficit")

    def __init__(self):
        self.queues: dict[str, deque] = {}
        self.ring: deque[str] = deque()
        self.deficit: dict[str, float] = {}

    def queue_for(self, tenant: str) -> deque:
        """The tenant's deque, registering the tenant in the rotation
        ring + deficit table on first sight — the ONE registration
        path (enqueue/requeue/restore all route here)."""
        q = self.queues.get(tenant)
        if q is None:
            q = self.queues[tenant] = deque()
            self.ring.append(tenant)
            self.deficit.setdefault(tenant, 0.0)
        return q

    def depth(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def oldest_head(self):
        """The longest-waiting queued request. Each tenant deque is
        FIFO by submit order (appendleft only ever fronts OLDER
        restored/parked items), so the per-tenant heads suffice —
        O(tenants), not O(backlog), which matters because this runs on
        every capacity miss in exactly the overload regime."""
        heads = [q[0] for q in self.queues.values() if q]
        return min(heads, key=lambda l: l.submit_t) if heads else None


class SchedulingPolicy:
    """Tiered + deficit-round-robin request ordering with projected-TTFT
    admission and preemption verdicts. See the module docstring for the
    semantics; see ``serve.scheduler`` for the call sites.

    ``registry`` (a :class:`~mpit_tpu.obs.stream.StreamRegistry`) feeds
    the projector; the Server binds its own via :meth:`bind_registry`
    when the policy was constructed without one.
    """

    def __init__(self, config: PolicyConfig | None = None, registry=None):
        self.cfg = config or PolicyConfig()
        self.projector = TTFTProjector(
            registry,
            quantile=self.cfg.projection_quantile,
            min_samples=self.cfg.min_samples,
        )
        self._tiers: dict[int, _TierState] = {}
        # Rolled into Server.stats()["policy"].
        self.preemptions = 0
        self.resumes = 0
        self.shed_admission = 0
        # The most recent admission verdict WITH the projection inputs
        # that produced it (ISSUE 16): the scheduler copies this into
        # the request's ledger so a "the projection lied" forensic can
        # replay the arithmetic months later. Overwritten per verdict —
        # the ledger is the durable store, not this field.
        self.last_admission: dict = {"verdict": "none"}
        # The queued head on whose behalf wants_preemption() last said
        # yes — the DISPLACING rid the victim's park event records.
        self.last_preemption_for: str = ""
        # (rid, tier, tenant) in SUCCESSFUL admit order — a failed
        # admission's restore() pops its entry back off. Bounded: a
        # long-running server must not spend memory on a diagnostic
        # (the fairness tests read windows far under the cap).
        self.admitted: deque = deque(maxlen=4096)

    def bind_registry(self, registry) -> None:
        if self.projector.registry is None:
            self.projector.registry = registry

    # -- queue surface -------------------------------------------------------
    def _tier(self, priority: int) -> _TierState:
        st = self._tiers.get(priority)
        if st is None:
            st = self._tiers[priority] = _TierState()
        return st

    def _weight(self, tenant: str) -> float:
        return float(self.cfg.tenant_weights.get(tenant, 1.0))

    def _cap(self, tenant: str) -> float:
        # Every tenant must be able to bank >= 1 request of credit, or
        # a tiny weight could starve it forever (and spin the rotation).
        return max(self.cfg.quantum * self._weight(tenant), 1.0)

    def enqueue(self, live) -> None:
        """Queue one request (``live`` is the scheduler's ``_Live``)."""
        st = self._tier(live.req.priority)
        st.queue_for(live.req.tenant or "").append(live)

    def requeue_front(self, live) -> None:
        """Park-and-resume path: a preempted request re-enters its own
        tier's tenant queue at the FRONT (it already waited its turn;
        making it re-earn credit would double-charge the preemption)."""
        st = self._tier(live.req.priority)
        st.queue_for(live.req.tenant or "").appendleft(live)

    def restore(self, live) -> None:
        """Undo one :meth:`next`: the admission attempt failed (no
        pages), so the request goes back to the head of its queue, the
        spent credit is refunded (transiently pushing the deficit at
        most 1 over its cap — the bounded-counter invariant's only
        excursion, erased by the next successful pop) and its
        ``admitted`` entry comes back off — the log records admissions
        that STUCK."""
        st = self._tier(live.req.priority)
        tenant = live.req.tenant or ""
        st.queue_for(tenant).appendleft(live)
        st.deficit[tenant] = st.deficit.get(tenant, 0.0) + 1.0
        if self.admitted and self.admitted[-1][0] == live.req.rid:
            self.admitted.pop()

    def pending(self) -> int:
        return sum(st.depth() for st in self._tiers.values())

    def depth_at_or_above(self, priority: int) -> int:
        """Queued requests a new ``priority``-class arrival would wait
        behind (its own tier + every higher one) — the projector's
        queue-depth input."""
        return sum(
            st.depth() for p, st in self._tiers.items() if p <= priority
        )

    def tier_depths(self) -> dict[int, int]:
        """Backlog per tier the run has seen — zeros INCLUDED, so a
        tier gauge reads 0 when its queue empties instead of latching
        its last nonzero value."""
        return {p: st.depth() for p, st in sorted(self._tiers.items())}

    # -- the DRR pop ---------------------------------------------------------
    def _next_in_tier(self, st: _TierState):
        if not any(st.queues.values()):
            return None
        # Each full rotation grants every non-empty tenant quantum×w
        # (capped at >= 1), so some deficit reaches 1.0 within
        # ceil(1/(q·w)) rotations of the slowest-earning tenant — the
        # loop bound is sized from that; hitting it is a real
        # accounting bug, not a low-weight tenant earning slowly.
        min_gain = min(
            (self.cfg.quantum * self._weight(t) for t in st.ring),
            default=1.0,
        )
        rotations = int(1.0 / min(min_gain, 1.0)) + 2
        for _ in range(rotations * (len(st.ring) + 1) + 1):
            tenant = st.ring[0]
            q = st.queues.get(tenant)
            if q and st.deficit.get(tenant, 0.0) >= 1.0:
                st.deficit[tenant] -= 1.0
                item = q.popleft()
                if not q:
                    # DRR no-banking rule: an emptied queue forfeits its
                    # balance — credit measures backlog service, not
                    # savings (this is what keeps counters bounded AND
                    # a returning burst from replaying banked credit).
                    st.deficit[tenant] = 0.0
                    st.ring.rotate(-1)
                return item
            # This tenant is done for the turn (empty, or out of
            # credit): move on, granting the NEXT tenant its arrival
            # credit — grants happen exactly once per rotation visit.
            if not q:
                st.deficit[tenant] = 0.0
            st.ring.rotate(-1)
            nxt = st.ring[0]
            if st.queues.get(nxt):
                st.deficit[nxt] = min(
                    st.deficit.get(nxt, 0.0) + self.cfg.quantum
                    * self._weight(nxt),
                    self._cap(nxt),
                )
        raise RuntimeError(
            "DRR rotation failed to converge — deficit accounting bug"
        )

    def next(self):
        """Pop the next request to admit: strict tier order, DRR within
        the tier. ``None`` when nothing is queued. Records the choice
        in ``admitted`` (the fairness tests' observable)."""
        for priority in sorted(self._tiers):
            item = self._next_in_tier(self._tiers[priority])
            if item is not None:
                self.admitted.append(
                    (item.req.rid, priority, item.req.tenant or "")
                )
                return item
        return None

    # -- admission (shed vs queue) -------------------------------------------
    # The verdict is ledgered at the SUBMIT seam (the scheduler emits
    # the admission event from last_admission right after this call —
    # emitting here too would double-count every verdict).
    # analysis: allow(ledger-seam)
    def should_shed(self, req) -> bool:
        """True when queueing ``req`` would already breach its TTFT
        target by projection — shedding now beats a guaranteed miss
        later. Requests without a target (``ttft_target_s <= 0``) are
        never admission-shed; cold windows abstain (admit). Every call
        records its verdict + projection inputs in ``last_admission``."""
        depth = self.depth_at_or_above(req.priority)
        verdict = {
            "queue_depth": depth,
            "ttft_target_s": req.ttft_target_s,
            "admission_factor": self.cfg.admission_factor,
            "proj_ttft_s": None,
        }
        self.last_admission = verdict
        if not self.cfg.admission or req.ttft_target_s <= 0:
            verdict["verdict"] = (
                "no_target" if self.cfg.admission else "disabled"
            )
            return False
        proj = self.projector.projected_ttft_s(depth)
        if proj is None:
            verdict["verdict"] = "abstain_cold"
            return False
        verdict["proj_ttft_s"] = proj
        shed = proj > self.cfg.admission_factor * req.ttft_target_s
        verdict["verdict"] = "shed" if shed else "admit"
        return shed

    # -- preemption ----------------------------------------------------------
    def wants_preemption(self, now: float):
        """The priority (tier) on whose behalf a preemption is
        justified RIGHT NOW, or ``None``: the best non-empty tier's
        longest-waiting request must carry a TTFT target and its
        waited-so-far + projected remaining wait must exceed it. Only
        the best tier is consulted — a lower tier never preempts."""
        if not self.cfg.preempt:
            return None
        for priority in sorted(self._tiers):
            st = self._tiers[priority]
            head = st.oldest_head()
            if head is None:
                continue
            if head.req.ttft_target_s <= 0:
                return None
            proj = self.projector.projected_ttft_s(
                max(st.depth() - 1, 0)
            )
            if proj is None:
                return None
            waited = now - head.submit_t
            if waited + proj > head.req.ttft_target_s:
                # The head this eviction serves — the victim's ledger
                # park event names it (the DISPLACING rid, ISSUE 16).
                self.last_preemption_for = head.req.rid
                return priority
            return None
        return None

    def pick_victim(self, live: Mapping[int, Any], priority: int):
        """The slot to evict for a ``priority``-tier admission: among
        LIVE lower-tier requests not already preempted out
        (``max_preemptions``), the one with the most generation left —
        evicting it buys the most slot/page time per eviction, and its
        re-prefill is the same price as anyone's. Ties break on slot id
        (determinism). ``None`` = nothing eligible."""
        best = None
        for slot in sorted(live):
            l = live[slot]
            if l.req.priority <= priority:
                continue
            if l.preempts >= self.cfg.max_preemptions:
                continue
            remaining = l.req.max_new_tokens - len(l.tokens)
            if remaining <= 0:
                continue
            if best is None or remaining > best[1]:
                best = (slot, remaining)
        return best[0] if best is not None else None

    # -- reporting -----------------------------------------------------------
    def stats(self) -> dict:
        out = {
            "preemptions": self.preemptions,
            "resumes": self.resumes,
            "shed_admission": self.shed_admission,
            "queued": self.pending(),
        }
        depths = self.tier_depths()
        if depths:
            out["tier_depths"] = depths
        return out
