"""mpit_tpu.comm — the in-tree TPU communication backend.

Replaces the reference's L-1/L0/L1 strata (libmpi + the ``mpiT.c`` Lua
binding + the ``mpiT`` Lua module; SURVEY.md §2) with:

- :mod:`mpit_tpu.comm.mesh` — bootstrap/topology: :func:`init` builds a
  :class:`World` (a named ``jax.sharding.Mesh`` + process info), the
  analogue of ``mpiT.Init()`` + ``Comm_rank``/``Comm_size`` — except rank
  and size come from the device topology (slice metadata / PJRT device
  list), not from ``mpirun``.
- :mod:`mpit_tpu.comm.collectives` — the collective API (allreduce,
  broadcast, reduce, allgather, reduce_scatter, alltoall, permute/shift,
  barrier, send/recv-style neighbor exchange) as ``shard_map``-friendly
  functions lowered to XLA collectives over ICI.
- :mod:`mpit_tpu.comm.pallas_ring` — the native tier: Pallas ring-DMA
  kernels (double-buffered ``make_async_remote_copy``) for ring
  all-gather / all-reduce, benchmarked for the "allreduce GB/s" metric.
"""

from mpit_tpu.comm.mesh import World, init, init_hybrid, get_world, local_mesh
from mpit_tpu.comm.collectives import (
    allgather,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    permute,
    pmean,
    rank,
    recv_from,
    reduce,
    reduce_scatter,
    send_to,
    shift,
    size,
    vary,
)

__all__ = [
    "World",
    "init",
    "init_hybrid",
    "get_world",
    "local_mesh",
    "allreduce",
    "allgather",
    "alltoall",
    "barrier",
    "broadcast",
    "permute",
    "pmean",
    "rank",
    "recv_from",
    "reduce",
    "reduce_scatter",
    "send_to",
    "shift",
    "size",
    "vary",
]
